"""Fleet-batched execution: one compiled program serves B simulations.

Every request to this framework is a (seed x scenario) simulation, and
until this module each one ran alone: ``Simulation`` compiles per
config shape and each ``run``/``run_bench`` call dispatches its own
whole-run program.  The kernels are op-*issue*-bound, not
bandwidth-bound (docs/PERF.md §3, §8) — at bench scale the machine
spends more time issuing per-tick ops and per-launch dispatches than
computing — so batching B independent runs into ONE compiled program
is the same microbatching lever every serving stack uses.  SWIM-style
membership runs are embarrassingly parallel across seeds: the batch
axis is exact, not approximate, and per-lane trajectories stay
bit-identical to sequential runs (tests/test_fleet.py).

Shape of the thing:

* **One program, B lanes.**  States and schedules are stacked on a
  leading batch axis; the tick function runs under ``jax.vmap`` inside
  one jitted ``lax.scan`` whose stacked carry is donated
  (``donate_argnums`` — the packed state planes are never copied
  between launches).  Seeds live in the Schedule arrays/PRNG keys, so
  one compiled program serves any fleet of the same config shape.
* **The clock is shared.**  Lanes tick in lockstep, so ``state.tick``
  stays an UNBATCHED scalar (``vmap`` ``in_axes=None``).  This is
  load-bearing: a batched clock would turn every clock-derived
  ``lax.cond`` (the overlay's SLOT_EPOCH re-slot pass) into a
  both-branches select — measured 16x extra re-slot work on CPU.
* **Batch-native kernels where vmap would destroy them.**  On TPU the
  overlay fleet rides the grid megakernel's explicit leading batch
  grid dimension (``grid = B x ticks x row-blocks``,
  ops/pallas/overlay_grid.py) — never ``jax.vmap``-of-``pallas_call``.
* **Trace mode stages events once per batch.**  The sparse
  device->host event encoding (core/sim._masks_to_host) runs over the
  whole (chunk*B, N, N) stack in one compaction pass.

Measured on this CPU-only image (docs/PERF.md §8): a B=8 fleet of
n=2048 overlay-churn seeds delivers ~3x the aggregate node-ticks/s of
8 sequential runs; the grader's three course scenarios run as a single
B=3 fleet (grader.grade_all_fleet).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimConfig
from ..state import (Schedule, WorldState, init_state,
                     make_schedule_host, pad_schedule_host)
from .sim import SimResult, _finish_masks_host, _pack_sparse
from .tick import TickEvents, make_tick

#: vmap axes of a stacked fleet: every lane carries its own arrays but
#: the CLOCK is shared (see module docstring), so ``tick`` is None
WORLD_AXES = WorldState(tick=None, in_group=0, own_hb=0, known=0, hb=0,
                        ts=0, gossip=0, gossip_age=0, joinreq=0,
                        joinrep=0, rng=0)
EVENT_AXES = TickEvents(added=0, removed=0, sent=0, recv=0)

#: Schedule axes when every lane shares one drop plan: the per-lane
#: injection arrays stay batched (seeds move victims), but
#: ``drop_active``/``drop_prob`` ride UNBATCHED, exactly like the
#: clock.  Load-bearing the same way the shared clock is: the drop
#: draw sits under a ``lax.cond`` on ``drop_active[t]``
#: (ops/drop.py), and a batched predicate degrades it to a
#: both-branches select — the per-tick threefry draw then runs on
#: EVERY tick of a no-drop config instead of never (measured 2.6x
#: the whole vmapped dense tick at n=24).  Lanes that genuinely
#: disagree on the drop plan fall back to SCHED_AXES_BATCHED.
SCHED_AXES_SHARED_DROP = Schedule(start_tick=0, fail_tick=0,
                                  rejoin_tick=0, drop_active=None,
                                  drop_prob=None,
                                  # exact-window scalars are inert on
                                  # this path (lane_drop_window off)
                                  # and a shared-drop bucket agrees on
                                  # them anyway
                                  drop_open=None, drop_close=None,
                                  # the partition WINDOW rides the
                                  # shared plane (window scalars are
                                  # config values the whole bucket
                                  # agrees on); the hashed group/flap/
                                  # link assignments are seed data and
                                  # stay per-lane
                                  part_group=0, part_on=None,
                                  part_open=None, part_close=None,
                                  link_prob=0, flap_mask=0,
                                  flap_phase=0, flap_period=0,
                                  flap_down=0, flap_close=0,
                                  byz_mask=0, byz_target=0, byz_boost=0,
                                  link_lat=0)
SCHED_AXES_BATCHED = Schedule(start_tick=0, fail_tick=0, rejoin_tick=0,
                              drop_active=0, drop_prob=0,
                              drop_open=0, drop_close=0,
                              part_group=0, part_on=0, part_open=0,
                              part_close=0, link_prob=0, flap_mask=0,
                              flap_phase=0, flap_period=0, flap_down=0,
                              flap_close=0, byz_mask=0, byz_target=0,
                              byz_boost=0, link_lat=0)
#: Canonical-bucket axes (service/canonical.py): lanes of ONE
#: equivalence class share the QUANTIZED superset drop window as the
#: unbatched cond predicate — exactly like SHARED_DROP keeps the draw
#: cond a real cond — while everything the class treats as a runtime
#: operand stays per-lane: drop probability, the EXACT window scalars
#: (re-applied by make_tick ``lane_drop_window``), the partition
#: window, byz_boost, the link matrices.  vmap keeps a cond whose
#: PREDICATE is unbatched a real cond even when branch operands are
#: batched, which is what makes per-lane drop_prob free here
#: (pinned by analysis/jaxpr_audit.py "fleet-dense-canonical").
SCHED_AXES_CANON = Schedule(start_tick=0, fail_tick=0, rejoin_tick=0,
                            drop_active=None, drop_prob=0,
                            drop_open=0, drop_close=0,
                            part_group=0, part_on=0, part_open=0,
                            part_close=0, link_prob=0, flap_mask=0,
                            flap_phase=0, flap_period=0, flap_down=0,
                            flap_close=0, byz_mask=0, byz_target=0,
                            byz_boost=0, link_lat=0)


def _shared_drop(cfgs) -> bool:
    """May the fleet share one unbatched drop/partition plan across
    lanes?  (The partition window gates sends exactly like the drop
    window, so it rides the same shared plane.)"""
    c0 = cfgs[0]
    return all((c.drop_msg, c.drop_open_tick, c.drop_close_tick,
                c.msg_drop_prob, c.partition_groups,
                c.partition_open_tick, c.partition_close_tick)
               == (c0.drop_msg, c0.drop_open_tick, c0.drop_close_tick,
                   c0.msg_drop_prob, c0.partition_groups,
                   c0.partition_open_tick, c0.partition_close_tick)
               for c in cfgs[1:])


def _stack_scheds(scheds, shared_drop: bool, stack=None):
    """Stack per-lane schedules; one shared drop plan when allowed.
    ``stack`` picks the stacking path (default eager
    :func:`stack_lanes`; the serving staging passes
    :func:`stack_lanes_host`) — ONE place owns the shared-drop
    reconstruction so the paths cannot diverge."""
    if stack is None:
        stack = stack_lanes
    st = stack(scheds)
    if not shared_drop:
        return st
    return st.replace(
        drop_active=scheds[0].drop_active,
        drop_prob=scheds[0].drop_prob,
        part_on=scheds[0].part_on,
        part_open=scheds[0].part_open,
        part_close=scheds[0].part_close)


def _check_stackable(trees) -> None:
    """Reject mismatched lanes up front, naming lane and field —
    ``jnp.stack`` (or worse, the vmapped program it feeds) would
    otherwise fail deep inside tracing with no hint of which request
    caused it."""
    paths0, treedef0 = jax.tree_util.tree_flatten_with_path(trees[0])
    for i, t in enumerate(trees[1:], start=1):
        paths, treedef = jax.tree_util.tree_flatten_with_path(t)
        if treedef != treedef0:
            raise ValueError(
                f"lane {i} has a different pytree structure than lane 0 "
                f"({treedef} != {treedef0}); fleets stack same-shape "
                "lanes only")
        for (p0, leaf0), (p, leaf) in zip(paths0, paths):
            s0 = jnp.shape(leaf0)
            s = jnp.shape(leaf)
            if s != s0:
                field = jax.tree_util.keystr(p)
                raise ValueError(
                    f"lane {i} field {field} has shape {s}, but lane 0 "
                    f"has {s0}; fleets stack same-shape lanes only "
                    "(check the lane's config: peer count and tick "
                    "count set these shapes)")


def stack_lanes(trees):
    """Stack same-shape pytrees on a new leading lane axis (eager:
    one ``jnp.stack`` dispatch per leaf).  The serving launch paths
    stage SCHEDULES host-side instead (:func:`stack_lanes_host`) and
    build states through the batched init programs
    (``_dense_init_stacked``/``_overlay_init_stacked``)."""
    trees = list(trees)
    _check_stackable(trees)
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


@jax.jit
def _stack_pytrees(trees):
    """One compiled program stacks a whole lane tuple (jit caches the
    trace per (treedef, avals), so each lane geometry compiles once)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def stack_lanes_jit(trees):
    """:func:`stack_lanes` semantics through ONE jitted program — for
    lane trees whose leaves already live on device (where the host
    variant would force per-leaf round-trips).  Not on the serving
    path today; pinned against the other variants by
    tests/test_fleet.py::test_stack_lanes_variants_agree."""
    trees = list(trees)
    _check_stackable(trees)
    return _stack_pytrees(tuple(trees))


def stack_lanes_host(trees):
    """:func:`stack_lanes` semantics in pure host numpy — ZERO device
    ops on the pack path.  The serving launch paths stack SCHEDULES
    this way (their leaves are numpy scalars/arrays by construction,
    models/overlay.make_overlay_schedule /
    state.make_schedule_host): the
    stacked tree enters device code as ordinary call inputs, so
    staging cannot queue behind — or contend with — an in-flight
    fleet program."""
    trees = list(trees)
    _check_stackable(trees)
    return jax.tree.map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]), *trees)


def _stack_states(states):
    """Stack per-lane states, keeping the shared clock a scalar."""
    st = stack_lanes(states)
    return st.replace(tick=states[0].tick)


def _embed_state_host(state_a, n: int):
    """numpy twin of core/dense_corner._embed_state for the fleet's
    resolve path, which must stay free of device ops — the pipelined
    fetch runs while the NEXT batch's program executes, so an eager
    jnp embed would queue behind (or contend with) it.  Inputs are
    the device_get'd per-lane corner states."""
    a = state_a.known.shape[0]

    def vec(v):
        out = np.zeros((n,), v.dtype)
        out[:a] = v
        return out

    def plane(p):
        out = np.zeros((n, n), p.dtype)
        out[:a, :a] = p
        return out

    return WorldState(
        tick=state_a.tick, rng=state_a.rng,
        in_group=vec(state_a.in_group), own_hb=vec(state_a.own_hb),
        known=plane(state_a.known), hb=plane(state_a.hb),
        ts=plane(state_a.ts), gossip=plane(state_a.gossip),
        gossip_age=plane(state_a.gossip_age),
        joinreq=vec(state_a.joinreq), joinrep=vec(state_a.joinrep))


def _slice_state_host(state, n: int):
    """Inverse of :func:`_embed_state_host`: the real ``n x n`` corner
    of a rung-width state (host numpy views).  The canonical fleet
    path (service/canonical.py) runs lanes at their pad-ladder rung
    and hands back real-width results only — filler peers' rows are
    identically zero by the inert-schedule construction and are never
    surfaced."""
    return WorldState(
        tick=state.tick, rng=state.rng,
        in_group=state.in_group[:n], own_hb=state.own_hb[:n],
        known=state.known[:n, :n], hb=state.hb[:n, :n],
        ts=state.ts[:n, :n], gossip=state.gossip[:n, :n],
        gossip_age=state.gossip_age[:n, :n],
        joinreq=state.joinreq[:n], joinrep=state.joinrep[:n])


def _lane_state(states, i: int):
    """Per-lane view of a stacked state (shared scalar clock)."""
    return type(states)(**{
        f.name: (getattr(states, f.name) if f.name == "tick"
                 else getattr(states, f.name)[i])
        for f in dataclasses.fields(type(states))})


def fleet_shape_key(cfg: SimConfig):
    """The config bits ONE compiled fleet program bakes in.

    Two configs with equal keys may ride the same program: everything
    else (seeds, victim windows, drop probabilities/windows, start
    ramps) flows through the Schedule arrays as data.  The overlay
    model compiles far more of the config statically (kernel phase
    elision, closed-form schedule constants), so its lanes must agree
    on everything but the seed.
    """
    if cfg.model == "overlay":
        return ("overlay", cfg.replace(seed=0))
    return ("full_view", cfg.n, cfg.t_remove, cfg.total_ticks,
            cfg.rejoin_after is None, cfg.worlds_key())


def _shape_mismatch(fleet_cfg: SimConfig, lane_cfg: SimConfig) -> str:
    """Name the config fields that break a lane's shape compatibility.

    Listing ``field=lane_value != fleet_value`` per offending field
    turns "failed deep inside vmap" into an actionable message: the
    caller learns exactly which knob (peer count, tick count, a whole
    overlay field) to fix on which lane.
    """
    if lane_cfg.model != fleet_cfg.model:
        return (f"model={lane_cfg.model!r} != fleet "
                f"model={fleet_cfg.model!r}")
    if fleet_cfg.model == "overlay":
        # the overlay compiles ~the whole config statically, so every
        # non-seed field is shape-relevant
        names = [f.name for f in dataclasses.fields(SimConfig)
                 if f.name != "seed"]
    else:
        names = ["max_nnb", "t_remove", "total_ticks",
                 # the adversarial worlds are static tick branches
                 "partition_groups", "partition_open_tick",
                 "partition_close_tick", "asym_drop", "wave_size",
                 "wave_tick", "wave_speed", "zombie", "flap_rate",
                 "flap_period", "flap_down", "flap_open_tick",
                 "flap_close_tick"]
    diffs = [f"{n}={getattr(lane_cfg, n)!r} != fleet "
             f"{n}={getattr(fleet_cfg, n)!r}"
             for n in names
             if getattr(lane_cfg, n) != getattr(fleet_cfg, n)]
    if fleet_cfg.model != "overlay" and \
            (lane_cfg.rejoin_after is None) != (fleet_cfg.rejoin_after is None):
        diffs.append(f"rejoin_after={lane_cfg.rejoin_after!r} != fleet "
                     f"rejoin_after={fleet_cfg.rejoin_after!r}")
    return ", ".join(diffs) or "(keys differ)"


#: Compiled fleet programs, shared across FleetSimulation instances
#: (exactly like core/tick._RUN_CACHE for single runs).  Keys carry
#: the fleet shape key, the segment-plan signature, the MESH slot
#: (None on the single-device path; the lane-mesh descriptor on
#: parallel/fleet_mesh.py's — a device-count change can never be
#: served a stale program), and the batch geometry; misses are
#: counted through core.tick.note_build so the serving layer's "one
#: build per distinct bucket key" contract is a run_build_count delta.
_FLEET_FN_CACHE: dict = {}


def _fleet_fn(key, builder):
    if key not in _FLEET_FN_CACHE:
        from .tick import note_build
        note_build()
        _FLEET_FN_CACHE[key] = builder()
    return _FLEET_FN_CACHE[key]


#: Cached lane-STAGING programs (batched init, jitted stack): tiny
#: jitted helpers that move lane assembly off the host.  Deliberately
#: NOT counted through core.tick.note_build — the serving layer's
#: one-build-per-bucket contract is about whole-run fleet programs,
#: and a staging helper compiling alongside the first dispatch must
#: not look like a second fleet build.
_STAGE_FN_CACHE: dict = {}


def _check_unstacked(lanes, n_real: int) -> None:
    """Filler-lane invariant, enforced at the unstack boundary: a
    fleet hands back EXACTLY its real lanes — one per request, filler
    never among them.  The serving layer zips lanes against requests,
    so a miscount here would silently mispair results (or strand
    handles); failing loudly turns it into an ordinary retryable
    dispatch error (service/resilience.py)."""
    if len(lanes) != n_real:
        raise RuntimeError(
            f"fleet unstacked {len(lanes)} lanes but n_real={n_real}; "
            "filler lanes must never be unstacked into results")


@dataclass
class FleetResult:
    """A finished fleet: per-lane results plus the one shared wall.

    ``lanes`` hold :class:`~..core.sim.SimResult` (dense model) or
    :class:`~..models.overlay.OverlayResult` (overlay) objects whose
    ``wall_seconds`` is the FLEET wall clock — a lane's own
    ``*_per_second`` therefore reads as "if I had run alone at fleet
    cost"; the aggregate properties below are the fleet's throughput.

    When the program executed with trailing filler lanes (a partial
    service batch padded to the compiled width, ``n_real=`` on
    :meth:`FleetSimulation.run`/:meth:`~FleetSimulation.run_bench`),
    ``lanes`` holds only the REAL lanes — filler results are never
    unstacked — and ``padded_batch``/``occupancy`` record the padding.
    """

    lanes: list
    wall_seconds: float
    #: compiled batch width actually dispatched (>= len(lanes) when
    #: filler lanes padded a partial batch; 0 = no padding happened)
    padded_batch: int = 0
    #: EXECUTE seconds: from the async program dispatch returning to
    #: the results being ready on device.  Under the pipelined serving
    #: path this span overlaps the host packing the next bucket, which
    #: is exactly why the scheduler accounts it as device wait
    #: (FleetService.stats decomposes pack / execute / fetch).  When
    #: the host out-runs the device the span is exact; when the host
    #: is still busy at readiness it is a tight upper bound (readiness
    #: is observed at the resolve-side block, which then returns
    #: immediately).
    device_seconds: float = 0.0
    #: PACK seconds: host-side lane staging (schedules, batched init,
    #: jitted stack) up to and including the async program dispatch.
    pack_seconds: float = 0.0
    #: FETCH seconds: host-side result transfer + unstack after the
    #: program completed.  ``wall_seconds == pack + execute + fetch``
    #: — the fleet's own cost, excluding any interleaved foreign work.
    fetch_seconds: float = 0.0

    @property
    def batch(self) -> int:
        return len(self.lanes)

    @property
    def occupancy(self) -> float:
        """Real-lane fraction of the dispatched program (1.0 unpadded)."""
        width = self.padded_batch or self.batch
        return self.batch / width if width else 0.0

    @property
    def total_node_ticks(self) -> int:
        return sum(r.cfg.n * r.ticks_run for r in self.lanes)

    @property
    def aggregate_node_ticks_per_second(self) -> float:
        if self.wall_seconds <= 0.0:
            return 0.0
        return self.total_node_ticks / self.wall_seconds

    @property
    def node_ticks_per_second_per_run(self) -> float:
        return self.aggregate_node_ticks_per_second / max(self.batch, 1)


@dataclass
class LaneCheckpoint:
    """One lane's resumable snapshot at a segment boundary.

    Everything here is HOST numpy: the lane's carry (``state``, the
    per-lane view of the stacked scan carry, shared clock excluded),
    the absolute clock of the snapshot (``tick`` — the plan position:
    the PR-1 segment planner's cuts are the only legal values,
    models/segments.checkpoint_ticks), and the per-leg outputs
    accumulated so far (``chunks``).  A checkpoint is therefore
    mesh-independent by construction — it can re-enter a fleet of any
    width on any mesh (the serving layer migrates checkpointed lanes
    across a mesh rebuild this way), and :func:`finish_lane` assembles
    the final per-lane result bit-identical to an uninterrupted run
    once the clock reaches ``total_ticks``.

    ``chunks`` format: overlay lanes accumulate per-leg
    ``OverlayMetrics`` structs (numpy leaves, each ``[leg_ticks]``);
    dense trace lanes accumulate ``(added, removed, sent, recv)``
    tuples (``added/removed`` ``[leg_ticks, N, N]``, counters
    ``[leg_ticks, N]``).
    """

    cfg: SimConfig
    mode: str                 # "trace" | "bench" (overlay: both run
    #                           the metrics path; dense bench-mode
    #                           runs cannot be checkpointed)
    tick: int                 # absolute clock of the carry
    state: dict               # {field: np.ndarray}, lane view, no tick
    chunks: list              # accumulated per-leg host outputs
    wall_seconds: float = 0.0  # accumulated across this lane's legs
    legs: int = 0             # legs executed so far
    #: mesh descriptor of the dispatch that produced this snapshot —
    #: the serving layer compares it against the current mesh to count
    #: lane migrations (a checkpoint itself is mesh-independent)
    mesh_desc: object = None

    @property
    def done(self) -> bool:
        return self.tick >= self.cfg.total_ticks

    def digest(self) -> str:
        """Stable short hash of the snapshot (clock + config + carry
        bytes).  The FULL config is folded in, not just the seed:
        lanes of different scenario variants can carry bit-identical
        state early in a run (a failure that has not fired yet), and
        their snapshots must not share a content address — they
        resume into different futures.  The durable spill tier
        (store/spill.py) keys files by this digest."""
        import hashlib
        h = hashlib.sha256()
        h.update(repr((self.tick, self.mode)).encode())
        h.update(repr(sorted(self.cfg.to_dict().items())).encode())
        for name in sorted(self.state):
            h.update(name.encode())
            h.update(np.ascontiguousarray(self.state[name]).tobytes())
        return h.hexdigest()[:16]


def finish_lane(ck: LaneCheckpoint):
    """Assemble a completed lane's final result from its checkpoint:
    the accumulated chunks stitched over the full horizon plus the
    final carry — bit-identical to the lane of an uninterrupted fleet
    run (tests/test_elastic.py).  Pure host work (no device ops): the
    serving layer calls this on the resolve path, where a device op
    could queue behind the next in-flight program."""
    if not ck.done:
        raise ValueError(
            f"lane at tick {ck.tick} of {ck.cfg.total_ticks} is not "
            "finished; resume it before assembling a result")
    if ck.cfg.model == "overlay":
        from ..models.overlay import (OverlayResult, OverlayState,
                                      make_overlay_schedule)
        metrics = jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
            *ck.chunks)
        final = OverlayState(tick=np.int32(ck.tick),
                             **{k: v for k, v in ck.state.items()})
        return OverlayResult(cfg=ck.cfg,
                             sched=make_overlay_schedule(ck.cfg),
                             final_state=final, metrics=metrics,
                             wall_seconds=ck.wall_seconds)
    sched = make_schedule_host(ck.cfg)
    added = np.concatenate([c[0] for c in ck.chunks], 0)
    removed = np.concatenate([c[1] for c in ck.chunks], 0)
    sent = np.concatenate([c[2] for c in ck.chunks], 0).T.copy()
    recv = np.concatenate([c[3] for c in ck.chunks], 0).T.copy()
    final = WorldState(tick=np.int32(ck.tick),
                       **{k: v for k, v in ck.state.items()})
    return SimResult(
        cfg=ck.cfg,
        start_tick=np.asarray(sched.start_tick),
        fail_tick=np.asarray(sched.fail_tick),
        rejoin_tick=np.asarray(sched.rejoin_tick),
        added=added, removed=removed, sent=sent, recv=recv,
        final_state=final, wall_seconds=ck.wall_seconds)


#: per-chunk array names of a dense trace chunk, in tuple order
#: (``LaneCheckpoint.chunks`` docstring above)
_DENSE_CHUNK_FIELDS = ("added", "removed", "sent", "recv")


def checkpoint_arrays(ck: LaneCheckpoint):
    """Flatten one :class:`LaneCheckpoint` into ``(meta, arrays)``.

    ``meta`` is a JSON-safe dict (config via ``SimConfig.to_dict``,
    clock, legs, chunk field order, and the snapshot's own
    :meth:`~LaneCheckpoint.digest`); ``arrays`` maps
    ``state/<field>`` and ``chunk/<j>/<field>`` to the snapshot's
    host-numpy leaves.  Pure host work — the durable spill tier
    (store/spill.py) writes exactly this pair to an npz, and
    :func:`checkpoint_from_arrays` rebuilds a bit-identical snapshot
    (digest-stable, so the spill file's content address survives the
    round trip).  ``mesh_desc`` is deliberately NOT serialized: a
    checkpoint is mesh-independent, and a reloaded one carries
    ``mesh_desc=None`` (the serving layer counts its next dispatch as
    a migration at most — never a correctness event).
    """
    arrays = {f"state/{k}": np.asarray(v) for k, v in ck.state.items()}
    chunk_fields = []
    for j, chunk in enumerate(ck.chunks):
        if hasattr(chunk, "sent") and not isinstance(chunk, tuple):
            names = tuple(f.name for f in dataclasses.fields(chunk))
            vals = [np.asarray(getattr(chunk, n)) for n in names]
        else:
            names = _DENSE_CHUNK_FIELDS
            vals = [np.asarray(v) for v in chunk]
        chunk_fields.append(list(names))
        for n, v in zip(names, vals):
            arrays[f"chunk/{j}/{n}"] = v
    meta = {"version": 1, "cfg": ck.cfg.to_dict(), "mode": ck.mode,
            "tick": int(ck.tick), "legs": int(ck.legs),
            "wall_seconds": float(ck.wall_seconds),
            "model": ck.cfg.model, "n_chunks": len(ck.chunks),
            "chunk_fields": chunk_fields, "digest": ck.digest()}
    return meta, arrays


def checkpoint_from_arrays(meta: dict, arrays: dict) -> LaneCheckpoint:
    """Inverse of :func:`checkpoint_arrays` (host numpy only).

    Overlay chunks are rebuilt as ``OverlayMetrics`` structs from the
    recorded field order; dense chunks as ``(added, removed, sent,
    recv)`` tuples.  The caller (store/spill.py ``fetch``) re-derives
    :meth:`LaneCheckpoint.digest` on the result and compares it to
    the file's content address, so a corrupted or mislabeled spill
    can never silently re-enter a fleet.
    """
    cfg = SimConfig.from_dict(meta["cfg"])
    state = {k.split("/", 1)[1]: np.asarray(v)
             for k, v in arrays.items() if k.startswith("state/")}
    chunks = []
    for j in range(meta["n_chunks"]):
        names = meta["chunk_fields"][j]
        vals = [np.asarray(arrays[f"chunk/{j}/{n}"]) for n in names]
        if cfg.model == "overlay":
            from ..models.overlay import OverlayMetrics
            chunks.append(OverlayMetrics(**dict(zip(names, vals))))
        else:
            chunks.append(tuple(vals))
    return LaneCheckpoint(cfg=cfg, mode=meta["mode"],
                          tick=int(meta["tick"]), state=state,
                          chunks=chunks,
                          wall_seconds=float(meta["wall_seconds"]),
                          legs=int(meta["legs"]), mesh_desc=None)


@dataclass
class FleetLeg:
    """One resolved leg of a checkpointed fleet dispatch: every real
    lane advanced to the leg's end cut, snapshotted host-side.

    ``lanes`` aliases ``checkpoints`` so the serving layer's
    per-lane machinery (fault-plane poisoning, count validation)
    treats a leg like any other resolved dispatch.  Timing fields
    describe THIS leg; each checkpoint's ``wall_seconds`` carries the
    lane's accumulated total."""

    checkpoints: list
    start: int
    ticks: int
    wall_seconds: float
    pack_seconds: float
    device_seconds: float
    fetch_seconds: float
    padded_batch: int

    @property
    def lanes(self) -> list:
        return self.checkpoints

    @property
    def batch(self) -> int:
        return len(self.checkpoints)

    @property
    def occupancy(self) -> float:
        width = self.padded_batch or self.batch
        return self.batch / width if width else 0.0

    @property
    def done(self) -> bool:
        return all(ck.done for ck in self.checkpoints)

    def results(self) -> FleetResult:
        """The final :class:`FleetResult` (``done`` legs only):
        per-lane results assembled from the accumulated chunks.
        ``wall_seconds`` is the ACCUMULATED fleet wall across every
        leg; the pack/execute/fetch decomposition is the final leg's
        (the per-leg columns were already reported per dispatch)."""
        lanes = [finish_lane(ck) for ck in self.checkpoints]
        _check_unstacked(lanes, len(self.checkpoints))
        wall = self.checkpoints[0].wall_seconds if self.checkpoints \
            else self.wall_seconds
        for lane in lanes:
            lane.wall_seconds = wall
        return FleetResult(
            lanes=lanes, wall_seconds=wall,
            padded_batch=self.padded_batch
            if len(self.checkpoints) < (self.padded_batch or 0) else 0,
            device_seconds=self.device_seconds,
            pack_seconds=self.pack_seconds,
            fetch_seconds=self.fetch_seconds)


class PendingFleet:
    """An in-flight fleet dispatch: the device program is launched
    (async), the results are not yet fetched.

    :meth:`resolve` blocks until the program completes, fetches and
    unstacks the results, and returns the :class:`FleetResult` —
    everything between launch and resolve is free host time, which is
    what the pipelined scheduler spends packing the NEXT bucket
    (service/scheduler.py).  ``pack_seconds`` is already final at
    launch; ``resolve`` is idempotent (the result is memoized).

    ``hold`` keeps the program's DONATED input buffers referenced
    until resolution.  Load-bearing: deleting a donated buffer whose
    consumer is still executing blocks the host thread until the
    program completes (measured ~the full execute time on XLA:CPU) —
    letting the staging locals die at the launch frame's return would
    silently re-serialize the very overlap this class exists for.
    The references are dropped after resolve, when deletion is free.

    ``launch(..., defer=True)`` stages the lanes but does NOT dispatch
    the program; :meth:`start` does.  The pipelined scheduler uses
    this to order one dispatch's work as stage(k+1) -> resolve(k) ->
    dispatch(k+1): staging overlaps batch k's execution, but batch
    k+1's program is not yet competing for cores when batch k's
    results are fetched.  (Dispatch-then-resolve was measured WORSE
    than synchronous on CPU: two big programs run concurrently on the
    shared thread pool and the fetch of k queues behind k+1.)
    ``pack_seconds`` at construction covers staging only; the final
    pack cost (staging + dispatch call) is on ``FleetResult``.

    Instances are INDEPENDENT ring slots (the PR 17 per-bucket
    in-flight rings stack ``pipeline_depth`` of them per bucket, any
    mix of buckets service-wide): every launch closes over its own
    staging state and result box, the donated placed inputs a mesh
    run wrapper parks on the shared program (``run.held``) are popped
    inside the SAME ``start()`` call that parked them (the scheduler
    starts batches one at a time on the host thread, so no window
    exists for one slot to take another's refs), and each slot's
    ``hold`` keeps its own donated buffers alive until its own
    resolve.  Nothing about staging, starting, waiting on, or
    resolving one slot reads or writes another's state — k
    concurrently started programs are safe (XLA serializes or
    overlaps them as the backend allows)."""

    def __init__(self, resolve_fn, pack_seconds: float, hold=None,
                 start_fn=None, wait_fn=None, probe_fn=None):
        self._resolve_fn = resolve_fn
        self.pack_seconds = pack_seconds
        self._result: Optional[FleetResult] = None
        self._hold = hold
        self._start_fn = start_fn
        self._wait_fn = wait_fn
        self._probe_fn = probe_fn

    def start(self) -> None:
        """Dispatch the staged program (no-op when already started; a
        FAILED dispatch is retained so a later call re-raises the real
        error — same contract as :meth:`wait`)."""
        if self._start_fn is not None:
            fn = self._start_fn
            fn()                  # may raise; keep fn for the re-raise
            self._start_fn = None

    @property
    def started(self) -> bool:
        """True once the program is dispatched — immediately so for
        launches the engine could not defer (the multi-chunk dense
        trace executes eagerly inside ``launch``); the pipelined
        scheduler checks this to fall back to the synchronous beat
        instead of pretending such a batch is in flight."""
        return self._start_fn is None

    def is_ready(self) -> bool:
        """True when the dispatched program's outputs are ready on
        device — WITHOUT blocking (False for a still-deferred launch).
        The scheduler's ``pump()`` uses this to harvest a finished
        in-flight batch opportunistically."""
        if self._start_fn is not None:
            return False
        if self._wait_fn is None:
            return True
        return bool(self._probe_fn()) if self._probe_fn is not None \
            else False

    def wait(self) -> None:
        """Block until the program's outputs are READY on device —
        without fetching them.  The pipelined scheduler calls this
        before dispatching the next batch's program, then fetches
        (:meth:`resolve`) while that program executes: the device
        never idles on host transfer work, and no two fleet programs
        ever compete for the cores.  Idempotent; the execute span ends
        here for timing purposes.  On failure the wait is RETAINED so
        a later :meth:`wait`/:meth:`resolve` re-raises the real device
        error instead of crashing on missing timing state."""
        self.start()
        if self._wait_fn is not None:
            fn = self._wait_fn
            fn()                  # may raise; keep fn for the re-raise
            self._wait_fn = None

    def resolve(self) -> FleetResult:
        """Idempotent: the result is memoized on success, and a
        FAILED resolution re-raises on every later call (the resolve
        step is retained) rather than silently returning None."""
        if self._resolve_fn is not None:
            self.wait()
            self._result = self._resolve_fn()
            self._resolve_fn = None
            self._hold = None      # program done; deletion is free now
        return self._result


def _pop_held(run):
    """Take (and clear) the donated placed-input refs a mesh run
    wrapper parked on itself (parallel/fleet_mesh.py ``_shard_run``);
    None for plain jitted programs, whose donated input the caller
    already owns."""
    held = getattr(run, "held", None)
    if held is not None:
        try:
            del run.held
        except AttributeError:
            pass
    return held


class FleetSimulation:
    """Run B same-shape simulations through one compiled program.

    Construct with the fleet's config shape, then call :meth:`run`
    (trace mode / overlay metrics mode) or :meth:`run_bench` (dense
    bench mode) with either ``seeds=[...]`` (the common case: distinct
    seeds of ``cfg``) or ``configs=[...]`` (same-shape configs — e.g.
    the grader's three course scenarios, whose differences are all
    Schedule data).  Compiled fleet programs are cached process-wide
    (``_FLEET_FN_CACHE``) per (shape key, segment-plan signature,
    mode, batch width, chunk length), so every FleetSimulation of the
    same shape shares one build — the serving layer
    (service/cache.py) leans on this for its one-build-per-bucket
    contract.

    ``n_real=k`` marks the trailing ``B - k`` lanes as FILLER: a
    partial batch padded up to an already-compiled width.  Filler
    lanes execute like any other lane but are masked out of the
    host-side result path — their events never enter the sparse
    device->host compaction (they cannot inflate its budget or flip
    it to the dense fallback) and they are never unstacked into
    ``FleetResult.lanes``.  vmap lanes are data-independent by
    construction (the only shared carry is the unbatched clock, which
    every lane advances identically), so filler cannot perturb real
    lanes' results — pinned bit-for-bit by
    tests/test_service.py::test_padding_parity.

    The vmapped paths force the pure-XLA tick (``use_pallas=False``):
    vmap-of-``pallas_call`` is never sound here, and the TPU fleet
    answer is the grid kernel's explicit batch grid dimension
    (models/overlay_grid.make_grid_fleet_run), which
    :func:`~..models.overlay.make_overlay_fleet_run` selects on TPU.
    """

    def __init__(self, cfg: SimConfig, block_size: int = 128,
                 chunk_ticks: Optional[int] = None):
        self.cfg = cfg
        self.block_size = block_size
        self.chunk_ticks = chunk_ticks
        # every _FLEET_FN_CACHE key this instance touched, so
        # evict_programs() drops exactly this bucket's programs — a
        # prefix match would also hit sibling buckets that share the
        # shape but differ in mode or drop probability
        self._program_keys: set = set()
        self._stage_keys: set = set()

    def _fleet_program(self, key, builder):
        self._program_keys.add(key)
        return _fleet_fn(key, builder)

    def _stage_fn(self, key, builder):
        """Cached lane-staging helper (batched init / jitted stack);
        see ``_STAGE_FN_CACHE`` for why these bypass note_build."""
        self._stage_keys.add(key)
        fn = _STAGE_FN_CACHE.get(key)
        if fn is None:
            fn = _STAGE_FN_CACHE[key] = builder()
        return fn

    @staticmethod
    def _resolve_n_real(batch: int, n_real) -> int:
        if n_real is None:
            return batch
        if not 1 <= n_real <= batch:
            raise ValueError(
                f"n_real={n_real} must be in [1, {batch}] (the fleet "
                f"dispatched {batch} lanes; filler lanes are the "
                "trailing ones)")
        return int(n_real)

    # ---- lane validation -------------------------------------------
    def _lane_cfgs(self, seeds, configs) -> list[SimConfig]:
        if (seeds is None) == (configs is None):
            raise ValueError("pass exactly one of seeds= or configs=")
        if configs is None:
            configs = [self.cfg.replace(seed=int(s)) for s in seeds]
        configs = list(configs)
        if not configs:
            raise ValueError("empty fleet")
        key = fleet_shape_key(self.cfg)
        for i, c in enumerate(configs):
            if fleet_shape_key(c) != key:
                raise ValueError(
                    f"lane {i} does not share the fleet's compiled "
                    f"shape: {_shape_mismatch(self.cfg, c)}; fleets "
                    "batch same-shape simulations only")
        return configs

    # ---- shared program cache ---------------------------------------
    def _mesh_entry(self):
        """The mesh slot of this fleet's program-cache keys.

        ``None`` here (single-device); parallel/fleet_mesh.py overrides
        with the lane-mesh descriptor, so a device-count change is a
        different key — never a stale program.
        """
        return None

    def _key_prefix(self) -> tuple:
        from ..models.segments import plan_signature
        return (fleet_shape_key(self.cfg), plan_signature(self.cfg),
                self.block_size, self._mesh_entry())

    def _cache_key(self, *extra):
        return self._key_prefix() + extra

    # ---- device-resident lane staging (PR 6) -------------------------
    def _staging_out_shardings(self, axes_tree):
        """Output shardings for the staged (batched) init state —
        ``None`` here; the mesh subclass returns the lane-sharded
        NamedSharding tree so staged states are BORN placed and the
        run wrapper's device_put degenerates to a no-op."""
        return None

    def _dense_init_stacked(self, cfg: SimConfig, b: int):
        """ONE cached jitted program builds the stacked tick-0 dense
        world: shared scalar clock, per-lane PRNG keys derived from a
        seed vector on device.  Replaces B host-side ``init_state``
        calls (9 eager dispatches each) plus a per-leaf stack — lane
        assembly becomes device work the pipelined scheduler can
        overlap."""
        key = ("dense_init", cfg.n, b, self._mesh_entry())

        def build():
            sh = self._staging_out_shardings(WORLD_AXES)
            kw = {} if sh is None else {"out_shardings": sh}

            @partial(jax.jit, **kw)
            def init(seeds):
                st = init_state(cfg)
                batched = {
                    f.name: jnp.broadcast_to(
                        getattr(st, f.name),
                        (b,) + jnp.shape(getattr(st, f.name)))
                    for f in dataclasses.fields(WorldState)
                    if f.name not in ("tick", "rng")}
                # per-lane PRNG keys: threefry_seed traced over the
                # seed vector is bit-identical to the per-lane
                # jax.random.PRNGKey(seed) a solo run builds
                return WorldState(
                    tick=st.tick,
                    rng=jax.vmap(jax.random.PRNGKey)(seeds), **batched)

            return init

        return self._stage_fn(key, build)

    def _overlay_init_stacked(self, b: int):
        """Cached jitted batched overlay init: every lane's tick-0
        state is identical (seed only enters through the Schedule), so
        the stacked init is a single broadcast program — no per-lane
        host init, no stack."""
        key = ("overlay_init", self.cfg.replace(seed=0), b,
               self._mesh_entry())

        def build():
            from ..models.overlay import (OVERLAY_FLEET_STATE_AXES,
                                          init_overlay_state)
            cfg = self.cfg
            sh = self._staging_out_shardings(OVERLAY_FLEET_STATE_AXES)
            kw = {} if sh is None else {"out_shardings": sh}

            @partial(jax.jit, **kw)
            def init():
                st = init_overlay_state(cfg)
                batched = {
                    f.name: jnp.broadcast_to(
                        getattr(st, f.name),
                        (b,) + jnp.shape(getattr(st, f.name)))
                    for f in dataclasses.fields(type(st))
                    if f.name != "tick"}
                return type(st)(tick=st.tick, **batched)

            return init

        return self._stage_fn(key, build)

    def _stack_scheds_dev(self, scheds, shared_drop: bool):
        """:func:`_stack_scheds` semantics, staged host-side
        (:func:`stack_lanes_host` — zero device ops); the shared drop
        plan still rides UNBATCHED from lane 0."""
        return _stack_scheds(scheds, shared_drop,
                             stack=stack_lanes_host)

    def evict_programs(self) -> int:
        """Drop this handle's compiled programs from the process
        caches; returns how many were evicted.

        The serving layer's bounded ProgramCache calls this on LRU
        eviction so dropping a bucket handle actually frees its jitted
        executables rather than just the thin FleetSimulation wrapper.
        ``_FLEET_FN_CACHE`` eviction is exact — only keys THIS
        instance touched, so a sibling bucket sharing the shape (other
        mode, other drop probability) keeps its programs.  The
        single-device overlay path compiles through
        ``_OVERLAY_FLEET_CACHE`` instead, whose keys this class cannot
        enumerate per-instance; those are purged by seed-stripped
        config, which may also evict a mode-sibling overlay bucket of
        the identical config — one redundant rebuild, never a
        correctness issue.
        """
        n = 0
        for k in self._program_keys:
            if _FLEET_FN_CACHE.pop(k, None) is not None:
                n += 1
        self._program_keys.clear()
        for k in self._stage_keys:
            _STAGE_FN_CACHE.pop(k, None)
        self._stage_keys.clear()
        if self.cfg.model == "overlay" and self._mesh_entry() is None:
            from ..models.overlay import _OVERLAY_FLEET_CACHE
            shape = self.cfg.replace(seed=0)
            stale = [k for k in _OVERLAY_FLEET_CACHE if k[0] == shape]
            for k in stale:
                del _OVERLAY_FLEET_CACHE[k]
            n += len(stale)
        return n

    # ---- dense bench ------------------------------------------------
    def _dense_bench_fn(self, batch: int, width: int, shared_drop: bool):
        def build():
            cfg_w = self.cfg.replace(max_nnb=width)
            tick = make_tick(cfg_w, self.block_size, use_pallas=False,
                             with_events=False)
            axes = SCHED_AXES_SHARED_DROP if shared_drop \
                else SCHED_AXES_BATCHED
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                             out_axes=(WORLD_AXES, EVENT_AXES))
            total = self.cfg.total_ticks

            @partial(jax.jit, donate_argnums=(0,))
            def run(states: WorldState, scheds: Schedule):
                def step(carry, _):
                    carry, ev = vtick(carry, scheds)
                    return carry, (ev.sent, ev.recv)
                return jax.lax.scan(step, states, None, length=total)

            return run

        return self._fleet_program(self._cache_key("bench", batch, width,
                                         shared_drop), build)

    def run_bench(self, seeds=None, configs=None, warmup: bool = True,
                  n_real: Optional[int] = None) -> FleetResult:
        """Bench-mode fleet: whole runs on device, one shared timing.

        Mirrors ``Simulation.run_bench`` semantics per lane — always a
        tick-0 start, and when the config's schedule never starts
        peers past the static active bound the whole fleet executes on
        the corner width (core/dense_corner.py; the bound is
        config-derived, so every lane shares it).  Counters follow the
        same stream-width caveat (``SimResult.counter_stream_width``).
        ``n_real`` marks trailing lanes as filler (see class docs).
        """
        return self.launch_bench(seeds=seeds, configs=configs,
                                 warmup=warmup, n_real=n_real).resolve()

    def launch_bench(self, seeds=None, configs=None, warmup: bool = True,
                     n_real: Optional[int] = None,
                     defer: bool = False) -> PendingFleet:
        """:meth:`run_bench` split at the dispatch boundary: stage the
        lanes and launch the program (async), return a
        :class:`PendingFleet` whose ``resolve()`` blocks, fetches, and
        unstacks.  The pipelined scheduler packs the next bucket in
        between (service/scheduler.py); with ``defer=True`` the
        program is staged but not dispatched until ``start()``."""
        cfgs = self._lane_cfgs(seeds, configs)
        nr = self._resolve_n_real(len(cfgs), n_real)
        if self.cfg.model == "overlay":
            return self._overlay_launch(cfgs, warmup, nr, defer=defer)
        from .dense_corner import active_bound, bench_stream_width
        bounds = {active_bound(c) for c in cfgs}
        if len(bounds) != 1:
            raise ValueError(
                f"lanes disagree on the active corner bound {bounds}; "
                "a fleet compiles one width")
        a = bounds.pop()
        n = self.cfg.n
        total = self.cfg.total_ticks
        corner = 0 < a < n
        width = a if corner else n
        shared = _shared_drop(cfgs)
        run = self._dense_bench_fn(len(cfgs), width, shared)
        cfg_w = self.cfg.replace(max_nnb=width)
        init = self._dense_init_stacked(cfg_w, len(cfgs))
        seeds_v = np.asarray([c.seed for c in cfgs], np.int64)

        def stage():
            scheds = [make_schedule_host(c) for c in cfgs]
            if corner:
                from ..state import slice_schedule
                lane_scheds = [slice_schedule(s, a) for s in scheds]
            else:
                lane_scheds = scheds
            return scheds, self._stack_scheds_dev(lane_scheds, shared)

        if warmup:                        # compile outside the timing
            _, ss = stage()
            f, _ = run(init(seeds_v), ss)
            jax.block_until_ready(f.known)
        t0 = time.perf_counter()
        scheds, sscheds = stage()
        states0 = init(seeds_v)
        stage_s = time.perf_counter() - t0
        box: dict = {}

        def start():
            t_s0 = time.perf_counter()
            final, (sent, recv) = run(states0, sscheds)
            # filler slice dispatched here, chained on the program —
            # resolve must stay free of device ops (see _overlay_launch)
            box["out"] = (final, sent[:, :nr], recv[:, :nr])
            box["held"] = _pop_held(run)
            box["t_launch"] = time.perf_counter()
            box["pack"] = stage_s + (box["t_launch"] - t_s0)

        def wait():
            if "t_ready" not in box:
                jax.block_until_ready(box["out"][0].known)
                box["t_ready"] = time.perf_counter()

        def probe():
            return "t_ready" in box or bool(box["out"][0].known.is_ready())

        def resolve():
            final, sent, recv = box["out"]
            pack = box["pack"]
            execute = box["t_ready"] - box["t_launch"]
            t_f0 = time.perf_counter()
            # one batched device->host transfer for the whole fleet
            # (filler lanes sliced off on device first), then plain
            # numpy views per lane — no per-lane device slicing
            final_h = jax.device_get(final)
            if int(final_h.tick) != total:
                raise RuntimeError(
                    "fleet bench did not complete all ticks")
            sr = np.stack(jax.device_get((sent, recv)))
            lanes = []
            for i, (c, s) in enumerate(zip(cfgs[:nr], scheds[:nr])):
                fs = _lane_state(final_h, i)
                if corner:
                    fs = _embed_state_host(fs, n)
                cnt = np.zeros((2, total, n), np.int32)
                cnt[:, :, :width] = sr[:, :, i, :]
                lanes.append(SimResult(
                    cfg=c,
                    start_tick=np.asarray(s.start_tick),
                    fail_tick=np.asarray(s.fail_tick),
                    rejoin_tick=np.asarray(s.rejoin_tick),
                    added=None, removed=None,
                    sent=cnt[0].T.copy(), recv=cnt[1].T.copy(),
                    final_state=fs,
                    wall_seconds=0.0,
                    counter_stream_width=bench_stream_width(c),
                ))
            _check_unstacked(lanes, nr)
            fetch = time.perf_counter() - t_f0
            wall = pack + execute + fetch
            for lane in lanes:
                lane.wall_seconds = wall
            return FleetResult(
                lanes=lanes, wall_seconds=wall,
                padded_batch=len(cfgs) if nr < len(cfgs) else 0,
                device_seconds=execute, pack_seconds=pack,
                fetch_seconds=fetch)

        pending = PendingFleet(resolve, stage_s,
                               hold=(states0, sscheds, box),
                               start_fn=start, wait_fn=wait,
                               probe_fn=probe)
        if not defer:
            pending.start()
        return pending

    # ---- dense trace -------------------------------------------------
    def _dense_trace_fn(self, batch: int, length: int, shared_drop: bool):
        def build():
            tick = make_tick(self.cfg, self.block_size, use_pallas=False,
                             with_events=True)
            axes = SCHED_AXES_SHARED_DROP if shared_drop \
                else SCHED_AXES_BATCHED
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                             out_axes=(WORLD_AXES, EVENT_AXES))

            @partial(jax.jit, donate_argnums=(0,))
            def run(states: WorldState, scheds: Schedule):
                def step(carry, _):
                    return vtick(carry, scheds)
                return jax.lax.scan(step, states, None, length=length)

            return run

        return self._fleet_program(self._cache_key("trace", batch, length,
                                         shared_drop), build)

    def run(self, seeds=None, configs=None, n_real: Optional[int] = None,
            warmup: bool = True) -> FleetResult:
        """Trace-mode fleet (dense): full event masks for every lane.

        Chunked over ticks like ``Simulation.run`` (the per-chunk
        device budget is divided by B), with the sparse event staging
        done ONCE across the whole batch per chunk.  Overlay configs
        dispatch to the metrics-mode fleet (the overlay has no dense
        event masks by design); ``warmup`` only affects that path —
        the service scheduler passes ``False`` so a dispatch never
        executes its fleet twice just to exclude compile time from
        ``wall_seconds``.  ``n_real`` marks trailing lanes as filler:
        they run on device but are masked out of the event staging and
        result unstacking entirely (see class docs).
        """
        return self.launch(seeds=seeds, configs=configs, n_real=n_real,
                           warmup=warmup).resolve()

    def _dense_trace_stage_device(self, ev, length: int, nr: int):
        """Dispatch the DEVICE half of one chunk's event staging
        (sparse compaction over the whole (length*n_real, N, N) stack
        + counter slice/cast), chained asynchronously on the run
        program.  Filler lanes are sliced off ON DEVICE first, so
        their events can neither inflate the sparse budget nor tip
        the transfer into the dense fallback.  The pipelined launch
        calls this at dispatch time so the resolve side is pure host
        fetch (:meth:`_dense_trace_finish_host`)."""
        n = self.cfg.n
        nw = (n + 31) // 32
        cap = max(1 << 14, (2 * length * nr * n * nw) // 16)
        a = ev.added[:, :nr].reshape(length * nr, n, n)
        r = ev.removed[:, :nr].reshape(length * nr, n, n)
        packed = _pack_sparse(a, r, cap=cap) \
            if length * nr > 0 and n >= 2 else None
        if n <= 8192:
            sr = jnp.stack([ev.sent, ev.recv])[:, :, :nr] \
                .astype(jnp.int16)
        else:
            sr = jnp.stack([ev.sent, ev.recv])[:, :, :nr]
        return (a, r, packed, sr, cap, length)

    def _dense_trace_finish_host(self, staged, nr: int):
        """Host half of one chunk's event staging: transfer + unpack
        the pre-dispatched compaction outputs."""
        a, r, packed, sr, cap, length = staged
        n = self.cfg.n
        if packed is None:
            a_h, r_h = np.asarray(a), np.asarray(r)
        else:
            a_h, r_h = _finish_masks_host(a, r, *packed, cap)
        sr_h = np.asarray(sr).astype(np.int32, copy=False)
        return (a_h.reshape(length, nr, n, n),
                r_h.reshape(length, nr, n, n), sr_h[0], sr_h[1])

    def _dense_trace_lanes(self, cfgs, scheds, final_h, nr,
                           added, removed, sent, recv):
        lanes = []
        for i, (c, s) in enumerate(zip(cfgs[:nr], scheds[:nr])):
            lanes.append(SimResult(
                cfg=c,
                start_tick=np.asarray(s.start_tick),
                fail_tick=np.asarray(s.fail_tick),
                rejoin_tick=np.asarray(s.rejoin_tick),
                added=np.concatenate([ch[:, i] for ch in added], 0),
                removed=np.concatenate([ch[:, i] for ch in removed], 0),
                sent=np.concatenate([ch[:, i] for ch in sent], 0).T.copy(),
                recv=np.concatenate([ch[:, i] for ch in recv], 0).T.copy(),
                final_state=_lane_state(final_h, i),
                wall_seconds=0.0,
            ))
        _check_unstacked(lanes, nr)
        return lanes

    def launch(self, seeds=None, configs=None,
               n_real: Optional[int] = None,
               warmup: bool = True, defer: bool = False) -> PendingFleet:
        """:meth:`run` split at the dispatch boundary (see
        :meth:`launch_bench`).  Single-segment traces (the common
        serving shape: the whole run fits one chunk) launch async;
        multi-chunk traces execute the chunked transfer loop eagerly —
        that loop is itself a host-device pipeline — and hand back a
        pre-resolved :class:`PendingFleet` (``defer`` has no effect
        there)."""
        cfgs = self._lane_cfgs(seeds, configs)
        nr = self._resolve_n_real(len(cfgs), n_real)
        if self.cfg.model == "overlay":
            return self._overlay_launch(cfgs, warmup=warmup, n_real=nr,
                                        defer=defer)
        b = len(cfgs)
        n = self.cfg.n
        total = self.cfg.total_ticks
        chunk = self.chunk_ticks
        if chunk is None:
            per_tick = 2 * n * n * b
            chunk = max(1, min(total, (1 << 30) // max(per_tick, 1)))
        shared = _shared_drop(cfgs)
        init = self._dense_init_stacked(self.cfg, b)
        seeds_v = np.asarray([c.seed for c in cfgs], np.int64)
        t0 = time.perf_counter()
        scheds = [make_schedule_host(c) for c in cfgs]
        sscheds = self._stack_scheds_dev(scheds, shared)
        states0 = init(seeds_v)
        if chunk >= total:
            # single segment: one async dispatch; everything after the
            # program is resolve-side work.  states0 is DONATED, so it
            # must stay referenced until resolve (see PendingFleet)
            run = self._dense_trace_fn(b, total, shared)
            stage_s = time.perf_counter() - t0
            box: dict = {}

            def start():
                t_s0 = time.perf_counter()
                states, ev = run(states0, sscheds)
                # the event compaction + counter casts are dispatched
                # HERE, chained on the program — resolve stays free of
                # device ops that could queue behind the next batch
                box["out"] = (states,
                              self._dense_trace_stage_device(ev, total,
                                                             nr))
                box["held"] = _pop_held(run)
                box["t_launch"] = time.perf_counter()
                box["pack"] = stage_s + (box["t_launch"] - t_s0)

            def wait():
                if "t_ready" not in box:
                    jax.block_until_ready(box["out"][0].tick)
                    box["t_ready"] = time.perf_counter()

            def probe():
                return "t_ready" in box \
                    or bool(box["out"][0].tick.is_ready())

            def resolve():
                states, staged = box["out"]
                pack = box["pack"]
                execute = box["t_ready"] - box["t_launch"]
                t_f0 = time.perf_counter()
                a_h, r_h, s_h, r2_h = \
                    self._dense_trace_finish_host(staged, nr)
                final_h = jax.device_get(states)
                if int(final_h.tick) != total:
                    raise RuntimeError(
                        "fleet trace did not complete all ticks")
                lanes = self._dense_trace_lanes(
                    cfgs, scheds, final_h, nr, [a_h], [r_h], [s_h],
                    [r2_h])
                fetch = time.perf_counter() - t_f0
                wall = pack + execute + fetch
                for lane in lanes:
                    lane.wall_seconds = wall
                return FleetResult(lanes=lanes, wall_seconds=wall,
                                   padded_batch=b if nr < b else 0,
                                   device_seconds=execute,
                                   pack_seconds=pack,
                                   fetch_seconds=fetch)

            pending = PendingFleet(resolve, stage_s,
                                   hold=(states0, sscheds, box),
                                   start_fn=start, wait_fn=wait,
                               probe_fn=probe)
            if not defer:
                pending.start()
            return pending
        # multi-chunk: per-chunk compaction must stay inside the loop
        # (it bounds device memory), so this path stays synchronous
        pack = time.perf_counter() - t0
        added, removed, sent, recv = [], [], [], []
        t_dev = 0.0
        done = 0
        states = states0
        while done < total:
            length = min(chunk, total - done)
            run = self._dense_trace_fn(b, length, shared)
            t_dev0 = time.perf_counter()
            states, ev = run(states, sscheds)
            jax.block_until_ready(states.tick)
            t_dev += time.perf_counter() - t_dev0
            a_h, r_h, s_h, r2_h = self._dense_trace_finish_host(
                self._dense_trace_stage_device(ev, length, nr), nr)
            added.append(a_h)
            removed.append(r_h)
            sent.append(s_h)
            recv.append(r2_h)
            done += length
        final_h = jax.device_get(states)
        if int(final_h.tick) != total:
            raise RuntimeError("fleet trace did not complete all ticks")
        lanes = self._dense_trace_lanes(cfgs, scheds, final_h, nr,
                                        added, removed, sent, recv)
        wall = time.perf_counter() - t0
        fetch = max(0.0, wall - pack - t_dev)
        for lane in lanes:
            lane.wall_seconds = wall
        result = FleetResult(lanes=lanes, wall_seconds=wall,
                             padded_batch=b if nr < b else 0,
                             device_seconds=t_dev, pack_seconds=pack,
                             fetch_seconds=fetch)
        return PendingFleet(lambda: result, pack)

    # ---- checkpoint / resume legs (PR 8: elastic serving) ------------
    def _leg_state_fields(self, cls) -> list:
        return [f.name for f in dataclasses.fields(cls)
                if f.name != "tick"]

    def _snapshot_lane(self, final_h, i: int, cls) -> dict:
        """Host numpy view of lane ``i``'s carry (shared clock
        excluded; the LaneCheckpoint's ``tick`` is authoritative)."""
        return {name: np.asarray(getattr(final_h, name))[i]
                for name in self._leg_state_fields(cls)}

    def _resume_states(self, cks: list, cls, tick: int):
        """Re-stack per-lane host snapshots into the scan carry: a
        stacked numpy tree with the SHARED scalar clock — it enters
        the jitted leg program as ordinary call inputs (the mesh run
        wrapper places it with the canonical shardings)."""
        stacked = {name: np.stack([ck.state[name] for ck in cks])
                   for name in self._leg_state_fields(cls)}
        return cls(tick=np.int32(tick), **stacked)

    def _advance_checkpoints(self, cks, cfgs, mode: str, end: int,
                             nr: int, snap, chunk_of,
                             wall: float) -> list:
        """Build the leg's output checkpoints: lane ``i``'s new carry
        snapshot + its accumulated chunks (a fresh list per leg — a
        retried leg rebuilds from the PREVIOUS checkpoint, whose chunk
        list must stay untouched)."""
        out = []
        for i in range(nr):
            prev = cks[i] if cks is not None else None
            out.append(LaneCheckpoint(
                cfg=cfgs[i], mode=mode, tick=end, state=snap(i),
                chunks=(list(prev.chunks) if prev is not None else [])
                + [chunk_of(i)],
                wall_seconds=(prev.wall_seconds if prev is not None
                              else 0.0) + wall,
                legs=(prev.legs if prev is not None else 0) + 1,
                mesh_desc=self._mesh_entry()))
        return out

    def run_leg(self, seeds=None, configs=None, resume=None,
                ticks=None, n_real=None, width=None,
                mode: str = "trace") -> FleetLeg:
        """:meth:`launch_leg` + resolve."""
        return self.launch_leg(seeds=seeds, configs=configs,
                               resume=resume, ticks=ticks,
                               n_real=n_real, width=width,
                               mode=mode).resolve()

    def launch_leg(self, seeds=None, configs=None, resume=None,
                   ticks=None, n_real=None, width=None,
                   mode: str = "trace", defer: bool = False
                   ) -> PendingFleet:
        """Launch one resumable LEG of a fleet run: ``ticks`` ticks of
        the scan, starting from tick 0 (``seeds=``/``configs=``, the
        ordinary staged init) or from a batch of
        :class:`LaneCheckpoint` snapshots (``resume=``).  The
        resolved :class:`PendingFleet` yields a :class:`FleetLeg`
        whose checkpoints re-enter this method until ``done``, at
        which point :meth:`FleetLeg.results` assembles per-lane
        results BIT-IDENTICAL to an uninterrupted run — the schedule
        is closed-form in the absolute clock carried in the scan
        state, so a shorter scan resumes mid-run exactly
        (tests/test_elastic.py).

        Snapshot discipline: leg boundaries must land on the PR-1
        segment planner's cuts (models/segments.checkpoint_ticks) —
        or the run's end — so the grid path's phase elision stays
        static across a resume (docs/PERF.md §7).  Resumed lanes must
        agree on the clock (a fleet shares ONE unbatched scan clock)
        and are padded to ``width`` by replicating lane 0's snapshot
        (filler lanes are data-independent and masked out, so any
        well-shaped carry is inert).

        Supported paths: every overlay request, and dense ``trace``
        mode.  Dense ``bench`` mode compiles the active-corner width
        into its whole-run program and is served monolithically
        (service/scheduler.py leaves it un-checkpointed).
        """
        from ..models.segments import checkpoint_ticks
        if resume is None:
            cfgs = self._lane_cfgs(seeds, configs)
            nr = self._resolve_n_real(len(cfgs), n_real)
            cks = None
            start = 0
        else:
            if seeds is not None or configs is not None:
                raise ValueError(
                    "pass resume= alone (the checkpoints carry their "
                    "own configs)")
            cks = list(resume)
            if not cks:
                raise ValueError("empty resume batch")
            t0s = {ck.tick for ck in cks}
            if len(t0s) != 1:
                raise ValueError(
                    f"resumed lanes disagree on the clock "
                    f"{sorted(t0s)}; a fleet shares ONE scan clock — "
                    "batch same-tick checkpoints only")
            modes = {ck.mode for ck in cks}
            if len(modes) != 1:
                raise ValueError(f"resumed lanes mix modes {modes}")
            mode = modes.pop()
            start = t0s.pop()
            nr = len(cks)
            w = nr if width is None else int(width)
            if w < nr:
                raise ValueError(f"width={w} < {nr} resumed lanes")
            cks_p = cks + [cks[0]] * (w - nr)
            cfgs = [ck.cfg for ck in cks_p]
            self._lane_cfgs(None, cfgs)     # shape (+ mesh) validation
        total = self.cfg.total_ticks
        length = (total - start) if ticks is None else int(ticks)
        end = start + length
        if length < 1 or end > total:
            raise ValueError(
                f"leg [{start}, {end}) outside the run's "
                f"[0, {total}] horizon")
        cuts = set(checkpoint_ticks(self.cfg))
        if start != 0 and start not in cuts:
            raise ValueError(
                f"leg start {start} is not a segment cut "
                f"{sorted(cuts)}; segment boundaries are the only "
                "legal snapshot points (models/segments.py)")
        if end != total and end not in cuts:
            raise ValueError(
                f"leg end {end} is not a segment cut {sorted(cuts)} "
                "or the run's end; segment boundaries are the only "
                "legal snapshot points (models/segments.py)")
        if self.cfg.model == "overlay":
            return self._overlay_leg_launch(cfgs, cks, mode, start,
                                            length, nr, defer)
        if mode != "trace":
            raise NotImplementedError(
                "dense bench-mode runs compile their active-corner "
                "width whole-run and cannot be checkpointed; serve "
                "them monolithically")
        return self._dense_trace_leg_launch(cfgs, cks, start, length,
                                            nr, defer)

    def _overlay_leg_launch(self, cfgs, cks, mode: str, start: int,
                            length: int, nr: int,
                            defer: bool) -> PendingFleet:
        from ..models.overlay import OverlayState, make_overlay_schedule
        b = len(cfgs)
        end = start + length
        run = self._overlay_fleet_fn(b, length=length, start_tick=start)
        t0 = time.perf_counter()
        scheds = [make_overlay_schedule(c) for c in cfgs]
        sscheds = stack_lanes_host(scheds)
        if cks is None:
            states0 = self._overlay_init_stacked(b)()
        else:
            cks_p = cks + [cks[0]] * (b - nr)
            states0 = self._resume_states(cks_p, OverlayState, start)
        stage_s = time.perf_counter() - t0
        box: dict = {}

        def start_fn():
            t_s0 = time.perf_counter()
            final, metrics = run(states0, sscheds)
            box["out"] = (final, metrics if nr == b else
                          jax.tree.map(lambda m: m[:nr], metrics))
            box["held"] = _pop_held(run)
            box["t_launch"] = time.perf_counter()
            box["pack"] = stage_s + (box["t_launch"] - t_s0)

        def wait():
            if "t_ready" not in box:
                jax.block_until_ready(box["out"][0].ids)
                box["t_ready"] = time.perf_counter()

        def probe():
            return "t_ready" in box or bool(box["out"][0].ids.is_ready())

        def resolve():
            final, mets = box["out"]
            execute = box["t_ready"] - box["t_launch"]
            pack = box["pack"]
            t_f0 = time.perf_counter()
            metrics_h = jax.device_get(mets)
            final_h = jax.device_get(final)
            if int(final_h.tick) != end:
                raise RuntimeError(
                    f"fleet leg stopped at tick {int(final_h.tick)}, "
                    f"expected {end}")
            fetch = time.perf_counter() - t_f0
            wall = pack + execute + fetch
            new = self._advance_checkpoints(
                cks, cfgs, mode, end, nr,
                snap=lambda i: self._snapshot_lane(final_h, i,
                                                   OverlayState),
                chunk_of=lambda i: jax.tree.map(
                    lambda m, _i=i: np.asarray(m)[_i], metrics_h),
                wall=wall)
            return FleetLeg(checkpoints=new, start=start, ticks=length,
                            wall_seconds=wall, pack_seconds=pack,
                            device_seconds=execute, fetch_seconds=fetch,
                            padded_batch=b)

        pending = PendingFleet(resolve, stage_s,
                               hold=(states0, sscheds, box),
                               start_fn=start_fn, wait_fn=wait,
                               probe_fn=probe)
        if not defer:
            pending.start()
        return pending

    def _dense_trace_leg_launch(self, cfgs, cks, start: int,
                                length: int, nr: int,
                                defer: bool) -> PendingFleet:
        b = len(cfgs)
        n = self.cfg.n
        end = start + length
        shared = _shared_drop(cfgs)
        t0 = time.perf_counter()
        scheds = [make_schedule_host(c) for c in cfgs]
        sscheds = self._stack_scheds_dev(scheds, shared)
        if cks is None:
            init = self._dense_init_stacked(self.cfg, b)
            seeds_v = np.asarray([c.seed for c in cfgs], np.int64)
            states0 = init(seeds_v)
        else:
            cks_p = cks + [cks[0]] * (b - nr)
            states0 = self._resume_states(cks_p, WorldState, start)
        chunk = self.chunk_ticks
        if chunk is None:
            per_tick = 2 * n * n * b
            chunk = max(1, min(length, (1 << 30) // max(per_tick, 1)))

        def _leg(new_cks, pack, execute, fetch) -> FleetLeg:
            return FleetLeg(checkpoints=new_cks, start=start,
                            ticks=length,
                            wall_seconds=pack + execute + fetch,
                            pack_seconds=pack, device_seconds=execute,
                            fetch_seconds=fetch, padded_batch=b)

        def _snap_and_chunks(final_h, chunks, pack, execute, fetch):
            if int(final_h.tick) != end:
                raise RuntimeError(
                    f"fleet leg stopped at tick {int(final_h.tick)}, "
                    f"expected {end}")
            a_all = np.concatenate([c[0] for c in chunks], 0)
            r_all = np.concatenate([c[1] for c in chunks], 0)
            s_all = np.concatenate([c[2] for c in chunks], 0)
            r2_all = np.concatenate([c[3] for c in chunks], 0)
            wall = pack + execute + fetch
            return self._advance_checkpoints(
                cks, cfgs, "trace", end, nr,
                snap=lambda i: self._snapshot_lane(final_h, i,
                                                   WorldState),
                chunk_of=lambda i: (a_all[:, i], r_all[:, i],
                                    s_all[:, i], r2_all[:, i]),
                wall=wall)

        if chunk >= length:
            run = self._dense_trace_fn(b, length, shared)
            stage_s = time.perf_counter() - t0
            box: dict = {}

            def start_fn():
                t_s0 = time.perf_counter()
                states, ev = run(states0, sscheds)
                box["out"] = (states,
                              self._dense_trace_stage_device(ev, length,
                                                             nr))
                box["held"] = _pop_held(run)
                box["t_launch"] = time.perf_counter()
                box["pack"] = stage_s + (box["t_launch"] - t_s0)

            def wait():
                if "t_ready" not in box:
                    jax.block_until_ready(box["out"][0].tick)
                    box["t_ready"] = time.perf_counter()

            def probe():
                return "t_ready" in box \
                    or bool(box["out"][0].tick.is_ready())

            def resolve():
                states, staged = box["out"]
                pack = box["pack"]
                execute = box["t_ready"] - box["t_launch"]
                t_f0 = time.perf_counter()
                a_h, r_h, s_h, r2_h = \
                    self._dense_trace_finish_host(staged, nr)
                final_h = jax.device_get(states)
                fetch = time.perf_counter() - t_f0
                return _leg(_snap_and_chunks(
                    final_h, [(a_h, r_h, s_h, r2_h)], pack, execute,
                    fetch), pack, execute, fetch)

            pending = PendingFleet(resolve, stage_s,
                                   hold=(states0, sscheds, box),
                                   start_fn=start_fn, wait_fn=wait,
                                   probe_fn=probe)
            if not defer:
                pending.start()
            return pending
        # a leg bigger than the device event budget runs the chunked
        # transfer loop eagerly — itself a host-device pipeline — and
        # hands back a pre-resolved PendingFleet (same contract as the
        # multi-chunk launch(): ``started`` is True, so the pipelined
        # scheduler falls back to the synchronous beat)
        pack = time.perf_counter() - t0
        chunks = []
        t_dev = 0.0
        states = states0
        done = 0
        while done < length:
            ln = min(chunk, length - done)
            run = self._dense_trace_fn(b, ln, shared)
            t_dev0 = time.perf_counter()
            states, ev = run(states, sscheds)
            jax.block_until_ready(states.tick)
            t_dev += time.perf_counter() - t_dev0
            chunks.append(self._dense_trace_finish_host(
                self._dense_trace_stage_device(ev, ln, nr), nr))
            done += ln
        final_h = jax.device_get(states)
        wall = time.perf_counter() - t0
        fetch = max(0.0, wall - pack - t_dev)
        leg = _leg(_snap_and_chunks(final_h, chunks, pack, t_dev,
                                    fetch), pack, t_dev, fetch)
        return PendingFleet(lambda: leg, pack)

    def _overlay_fleet_fn(self, batch: int, length: Optional[int] = None,
                          start_tick: int = 0):
        """The overlay fleet's compiled program (the mesh subclass in
        parallel/fleet_mesh.py overrides this with the lane-sharded
        build).  ``length``/``start_tick`` scan a leg of the run from
        a pinned clock (checkpoint/resume, :meth:`launch_leg`; the
        start tick shapes only the TPU grid path's segment plan)."""
        from ..models.overlay import make_overlay_fleet_run
        return make_overlay_fleet_run(self.cfg, batch, length=length,
                                      start_tick=start_tick)

    # ---- overlay (metrics mode) --------------------------------------
    def _overlay_launch(self, cfgs: Sequence[SimConfig], warmup: bool,
                        n_real: Optional[int] = None,
                        defer: bool = False) -> PendingFleet:
        from ..models.overlay import OverlayResult, make_overlay_schedule
        b = len(cfgs)
        nr = self._resolve_n_real(b, n_real)
        total = self.cfg.total_ticks
        run = self._overlay_fleet_fn(b)
        init = self._overlay_init_stacked(b)

        if warmup:
            f, _ = run(init(), stack_lanes_host(
                [make_overlay_schedule(c) for c in cfgs]))
            jax.block_until_ready(f.ids)
        t0 = time.perf_counter()
        scheds = [make_overlay_schedule(c) for c in cfgs]
        sscheds = stack_lanes_host(scheds)
        states0 = init()
        stage_s = time.perf_counter() - t0
        box: dict = {}

        def start():
            t_s0 = time.perf_counter()
            final, metrics = run(states0, sscheds)
            # filler lanes are dropped on device before the (B, T)
            # metric stacks cross to host; the slice is dispatched
            # HERE (chained on the program) so resolve is pure host
            # fetch — no device op of batch k may queue behind batch
            # k+1's program
            box["out"] = (final, metrics if nr == b else
                          jax.tree.map(lambda m: m[:nr], metrics))
            box["held"] = _pop_held(run)
            box["t_launch"] = time.perf_counter()
            box["pack"] = stage_s + (box["t_launch"] - t_s0)

        def wait():
            if "t_ready" not in box:
                jax.block_until_ready(box["out"][0].ids)
                box["t_ready"] = time.perf_counter()

        def probe():
            return "t_ready" in box or bool(box["out"][0].ids.is_ready())

        def resolve():
            final, mets = box["out"]
            execute = box["t_ready"] - box["t_launch"]
            pack = box["pack"]
            t_f0 = time.perf_counter()
            # one batched device->host transfer each for metrics and
            # final state, then plain numpy views per lane
            metrics_h = jax.device_get(mets)
            final_h = jax.device_get(final)
            if int(final_h.tick) != total:
                raise RuntimeError("fleet overlay run did not complete")
            lanes = [OverlayResult(
                cfg=c, sched=scheds[i],
                final_state=_lane_state(final_h, i),
                metrics=jax.tree.map(lambda m, _i=i: m[_i], metrics_h),
                wall_seconds=0.0,
            ) for i, c in enumerate(cfgs[:nr])]
            _check_unstacked(lanes, nr)
            fetch = time.perf_counter() - t_f0
            wall = pack + execute + fetch
            for lane in lanes:
                lane.wall_seconds = wall
            return FleetResult(lanes=lanes, wall_seconds=wall,
                               padded_batch=b if nr < b else 0,
                               device_seconds=execute,
                               pack_seconds=pack, fetch_seconds=fetch)

        pending = PendingFleet(resolve, stage_s,
                               hold=(states0, sscheds, box),
                               start_fn=start, wait_fn=wait,
                               probe_fn=probe)
        if not defer:
            pending.start()
        return pending

class CanonicalFleetSimulation(FleetSimulation):
    """A fleet over one CANONICAL equivalence class (service/canonical
    .py): lanes whose exact configs differ — peer count below the same
    pad-ladder rung (drop-off classes), drop probability, phase-window
    jitter within the quantization grid, world operand values — ride
    ONE compiled program at the rung width.

    Mechanically this is the base dense fleet with ``self.cfg`` set to
    a RUNG-WIDTH representative (``member.replace(max_nnb=rung)``), so
    every inherited piece of machinery — the batched init, the event
    compaction, chunk budgeting — naturally operates at rung width.
    The canonical deltas are confined to:

    * lane validation by canonical key equality (not exact shape);
    * schedule staging: each lane's REAL-width schedule is padded to
      the rung with inert filler peers (state.pad_schedule_host) and
      the stacked ``drop_active`` is replaced by the class's shared
      QUANTIZED superset window (canonical_drop_active), with per-lane
      exact windows re-applied in the tick (make_tick
      ``lane_drop_window``) — the SCHED_AXES_CANON split;
    * the drop stream is drawn at the class's ``stream_n`` (the REAL
      peer count of drop-on classes) and corner-embedded, so padded
      lanes consume the byte-identical Bernoulli stream;
    * results are sliced back to each lane's real ``n`` host-side —
      filler PEERS, like filler lanes, are never unstacked.

    Per-lane results are bit-identical to exact unpadded solo runs
    (tests/test_canonical.py pins this per tick).  Monolithic trace
    dispatches only: bench mode bakes the active corner and checkpoint
    legs validate exact-plan cuts, so both keep exact buckets
    (canonical_supported routes them away before construction).
    """

    #: pad-ladder rung multiple (a power of two): the mesh-canonical
    #: subclass (parallel/fleet_mesh.py CanonicalMeshFleetSimulation)
    #: pins its full-strength peer-shard count here before chaining
    #: into this __init__, so rungs — and the canonical keys built
    #: from them — stay peer-shard-divisible
    _rung_multiple = 1

    def __init__(self, cfg: SimConfig, block_size: int = 128,
                 chunk_ticks: Optional[int] = None):
        from ..service.canonical import (canonical_bucket_key,
                                         canonical_supported,
                                         ladder_rung)
        if not canonical_supported(cfg, "trace"):
            raise ValueError(
                f"config (model={cfg.model!r}) is not canonicalizable; "
                "use FleetSimulation with the exact bucket key")
        self.member_cfg = cfg
        self.rung = ladder_rung(cfg.n, multiple=self._rung_multiple)
        self._canon_key = canonical_bucket_key(
            cfg, "trace", peers=self._rung_multiple)
        # the class's drop-stream width: real n for drop-on classes
        # (stream bit-identity pins it), None otherwise — mirrors the
        # stream_n component of canonical_fleet_shape_key
        self._stream_n = cfg.n if (cfg.drop_msg or cfg.asym_drop) \
            else None
        self._lane_drop = self._stream_n is not None
        super().__init__(cfg.replace(max_nnb=self.rung),
                         block_size=block_size, chunk_ticks=chunk_ticks)

    # ---- canonical lane validation ----------------------------------
    def _lane_cfgs(self, seeds, configs) -> list[SimConfig]:
        from ..service.canonical import canonical_bucket_key
        if (seeds is None) == (configs is None):
            raise ValueError("pass exactly one of seeds= or configs=")
        if configs is None:
            configs = [self.member_cfg.replace(seed=int(s))
                       for s in seeds]
        configs = list(configs)
        if not configs:
            raise ValueError("empty fleet")
        for i, c in enumerate(configs):
            k = canonical_bucket_key(c, "trace",
                                     peers=self._rung_multiple)
            if k != self._canon_key:
                raise ValueError(
                    f"lane {i} is not a member of this canonical "
                    f"equivalence class: {k} != {self._canon_key}")
        return configs

    def _key_prefix(self) -> tuple:
        # the canonical key IS the program identity (rung, stream_n,
        # static plane set, quantized plan) — exact member keys must
        # NOT enter, or the collapse would silently vanish
        return (self._canon_key, self.block_size, self._mesh_entry())

    # ---- canonical program ------------------------------------------
    def _canon_run_builder(self, length: int, batched_drop: bool = False):
        """UNJITTED canonical run builder (shared by the cached
        program below and the jaxpr audit, which also builds the
        ``batched_drop`` twin to prove the shared quantized window
        keeps strictly more real conds)."""
        na = self._stream_n if self._stream_n is not None else self.cfg.n
        tick = make_tick(self.cfg, self.block_size, use_pallas=False,
                         with_events=True, n_active=na,
                         lane_drop_window=self._lane_drop)
        axes = SCHED_AXES_BATCHED if batched_drop else SCHED_AXES_CANON
        vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                         out_axes=(WORLD_AXES, EVENT_AXES))

        def run(states: WorldState, scheds: Schedule):
            def step(carry, _):
                return vtick(carry, scheds)
            return jax.lax.scan(step, states, None, length=length)

        return run

    def _canon_trace_fn(self, batch: int, length: int):
        def build():
            return partial(jax.jit, donate_argnums=(0,))(
                self._canon_run_builder(length))
        return self._fleet_program(
            self._cache_key("canon-trace", batch, length), build)

    def _stack_scheds_canon(self, scheds):
        """Stack rung-padded lane schedules host-side; the shared
        drop plane is the class's quantized superset window (a pure
        function of the canonical key, so every member agrees)."""
        from ..service.canonical import canonical_drop_active
        st = stack_lanes_host(scheds)
        return st.replace(
            drop_active=canonical_drop_active(self.member_cfg))

    def _canon_trace_lanes(self, cfgs, scheds, final_h, nr,
                           added, removed, sent, recv):
        """Per-lane results sliced to each lane's REAL peer count —
        the pad-ladder twin of :meth:`_dense_trace_lanes`.  Filler
        peers (rows >= lane n) are never surfaced, mirroring the
        filler-LANE invariant (:func:`_check_unstacked`)."""
        lanes = []
        for i, (c, s) in enumerate(zip(cfgs[:nr], scheds[:nr])):
            n = c.n
            lanes.append(SimResult(
                cfg=c,
                start_tick=np.asarray(s.start_tick[:n]),
                fail_tick=np.asarray(s.fail_tick[:n]),
                rejoin_tick=np.asarray(s.rejoin_tick[:n]),
                added=np.concatenate(
                    [ch[:, i, :n, :n] for ch in added], 0),
                removed=np.concatenate(
                    [ch[:, i, :n, :n] for ch in removed], 0),
                sent=np.concatenate(
                    [ch[:, i, :n] for ch in sent], 0).T.copy(),
                recv=np.concatenate(
                    [ch[:, i, :n] for ch in recv], 0).T.copy(),
                final_state=_slice_state_host(_lane_state(final_h, i), n),
                wall_seconds=0.0))
        _check_unstacked(lanes, nr)
        return lanes

    def launch(self, seeds=None, configs=None,
               n_real: Optional[int] = None,
               warmup: bool = True, defer: bool = False) -> PendingFleet:
        """Monolithic canonical dense trace launch: the base
        single-segment async path at rung width over padded lanes."""
        cfgs = self._lane_cfgs(seeds, configs)
        nr = self._resolve_n_real(len(cfgs), n_real)
        b = len(cfgs)
        total = self.cfg.total_ticks
        per_tick = 2 * self.cfg.n * self.cfg.n * b
        if total * per_tick > (1 << 30):
            # the canonical path has no chunked fallback by design
            # (chunk boundaries would need exact-plan cut validation);
            # classes this large keep exact buckets
            raise ValueError(
                f"canonical trace event budget exceeded (rung="
                f"{self.cfg.n}, b={b}, ticks={total}); serve this "
                "config through the exact bucket path")
        init = self._dense_init_stacked(self.cfg, b)
        seeds_v = np.asarray([c.seed for c in cfgs], np.int64)
        t0 = time.perf_counter()
        scheds = [pad_schedule_host(make_schedule_host(c), self.rung)
                  for c in cfgs]
        sscheds = self._stack_scheds_canon(scheds)
        states0 = init(seeds_v)
        run = self._canon_trace_fn(b, total)
        stage_s = time.perf_counter() - t0
        box: dict = {}

        def start():
            t_s0 = time.perf_counter()
            states, ev = run(states0, sscheds)
            box["out"] = (states,
                          self._dense_trace_stage_device(ev, total, nr))
            box["held"] = _pop_held(run)
            box["t_launch"] = time.perf_counter()
            box["pack"] = stage_s + (box["t_launch"] - t_s0)

        def wait():
            if "t_ready" not in box:
                jax.block_until_ready(box["out"][0].tick)
                box["t_ready"] = time.perf_counter()

        def probe():
            return "t_ready" in box \
                or bool(box["out"][0].tick.is_ready())

        def resolve():
            states, staged = box["out"]
            pack = box["pack"]
            execute = box["t_ready"] - box["t_launch"]
            t_f0 = time.perf_counter()
            a_h, r_h, s_h, r2_h = \
                self._dense_trace_finish_host(staged, nr)
            final_h = jax.device_get(states)
            if int(final_h.tick) != total:
                raise RuntimeError(
                    "canonical fleet trace did not complete all ticks")
            lanes = self._canon_trace_lanes(
                cfgs, scheds, final_h, nr, [a_h], [r_h], [s_h], [r2_h])
            fetch = time.perf_counter() - t_f0
            wall = pack + execute + fetch
            for lane in lanes:
                lane.wall_seconds = wall
            return FleetResult(lanes=lanes, wall_seconds=wall,
                               padded_batch=b if nr < b else 0,
                               device_seconds=execute,
                               pack_seconds=pack, fetch_seconds=fetch)

        pending = PendingFleet(resolve, stage_s,
                               hold=(states0, sscheds, box),
                               start_fn=start, wait_fn=wait,
                               probe_fn=probe)
        if not defer:
            pending.start()
        return pending

    # modes the canonical path deliberately does not serve — the
    # serving layer's canonical_supported gate routes them to exact
    # buckets before a CanonicalFleetSimulation is ever constructed
    def run_bench(self, *a, **kw):
        raise NotImplementedError(
            "canonical buckets serve dense trace only; bench mode "
            "bakes the active-corner width and keeps exact buckets")

    def launch_bench(self, *a, **kw):
        raise NotImplementedError(
            "canonical buckets serve dense trace only; bench mode "
            "bakes the active-corner width and keeps exact buckets")

    def run_leg(self, *a, **kw):
        from ..service.canonical import CanonicalLegUnsupported
        raise CanonicalLegUnsupported(
            "canonical buckets serve monolithic traces only: "
            "checkpoint legs validate resume cuts against the EXACT "
            "segment plan, which canonical buckets quantize away — "
            "serve legged work from exact buckets "
            "(FleetService(canonicalize=False)); "
            "docs/SERVING.md 'Bucket canonicalization'")

    def launch_leg(self, *a, **kw):
        from ..service.canonical import CanonicalLegUnsupported
        raise CanonicalLegUnsupported(
            "canonical buckets serve monolithic traces only: "
            "checkpoint legs validate resume cuts against the EXACT "
            "segment plan, which canonical buckets quantize away — "
            "serve legged work from exact buckets "
            "(FleetService(canonicalize=False)); "
            "docs/SERVING.md 'Bucket canonicalization'")
