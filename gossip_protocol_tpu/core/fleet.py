"""Fleet-batched execution: one compiled program serves B simulations.

Every request to this framework is a (seed x scenario) simulation, and
until this module each one ran alone: ``Simulation`` compiles per
config shape and each ``run``/``run_bench`` call dispatches its own
whole-run program.  The kernels are op-*issue*-bound, not
bandwidth-bound (docs/PERF.md §3, §8) — at bench scale the machine
spends more time issuing per-tick ops and per-launch dispatches than
computing — so batching B independent runs into ONE compiled program
is the same microbatching lever every serving stack uses.  SWIM-style
membership runs are embarrassingly parallel across seeds: the batch
axis is exact, not approximate, and per-lane trajectories stay
bit-identical to sequential runs (tests/test_fleet.py).

Shape of the thing:

* **One program, B lanes.**  States and schedules are stacked on a
  leading batch axis; the tick function runs under ``jax.vmap`` inside
  one jitted ``lax.scan`` whose stacked carry is donated
  (``donate_argnums`` — the packed state planes are never copied
  between launches).  Seeds live in the Schedule arrays/PRNG keys, so
  one compiled program serves any fleet of the same config shape.
* **The clock is shared.**  Lanes tick in lockstep, so ``state.tick``
  stays an UNBATCHED scalar (``vmap`` ``in_axes=None``).  This is
  load-bearing: a batched clock would turn every clock-derived
  ``lax.cond`` (the overlay's SLOT_EPOCH re-slot pass) into a
  both-branches select — measured 16x extra re-slot work on CPU.
* **Batch-native kernels where vmap would destroy them.**  On TPU the
  overlay fleet rides the grid megakernel's explicit leading batch
  grid dimension (``grid = B x ticks x row-blocks``,
  ops/pallas/overlay_grid.py) — never ``jax.vmap``-of-``pallas_call``.
* **Trace mode stages events once per batch.**  The sparse
  device->host event encoding (core/sim._masks_to_host) runs over the
  whole (chunk*B, N, N) stack in one compaction pass.

Measured on this CPU-only image (docs/PERF.md §8): a B=8 fleet of
n=2048 overlay-churn seeds delivers ~3x the aggregate node-ticks/s of
8 sequential runs; the grader's three course scenarios run as a single
B=3 fleet (grader.grade_all_fleet).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimConfig
from ..state import Schedule, WorldState, init_state, make_schedule
from .sim import SimResult, _masks_to_host
from .tick import TickEvents, make_tick

#: vmap axes of a stacked fleet: every lane carries its own arrays but
#: the CLOCK is shared (see module docstring), so ``tick`` is None
WORLD_AXES = WorldState(tick=None, in_group=0, own_hb=0, known=0, hb=0,
                        ts=0, gossip=0, joinreq=0, joinrep=0, rng=0)
EVENT_AXES = TickEvents(added=0, removed=0, sent=0, recv=0)


def stack_lanes(trees):
    """Stack same-shape pytrees on a new leading lane axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _stack_states(states):
    """Stack per-lane states, keeping the shared clock a scalar."""
    st = stack_lanes(states)
    return st.replace(tick=states[0].tick)


def _lane_state(states, i: int):
    """Per-lane view of a stacked state (shared scalar clock)."""
    return type(states)(**{
        f.name: (getattr(states, f.name) if f.name == "tick"
                 else getattr(states, f.name)[i])
        for f in dataclasses.fields(type(states))})


def fleet_shape_key(cfg: SimConfig):
    """The config bits ONE compiled fleet program bakes in.

    Two configs with equal keys may ride the same program: everything
    else (seeds, victim windows, drop probabilities/windows, start
    ramps) flows through the Schedule arrays as data.  The overlay
    model compiles far more of the config statically (kernel phase
    elision, closed-form schedule constants), so its lanes must agree
    on everything but the seed.
    """
    if cfg.model == "overlay":
        return ("overlay", cfg.replace(seed=0))
    return ("full_view", cfg.n, cfg.t_remove, cfg.total_ticks,
            cfg.rejoin_after is None)


@dataclass
class FleetResult:
    """A finished fleet: per-lane results plus the one shared wall.

    ``lanes`` hold :class:`~..core.sim.SimResult` (dense model) or
    :class:`~..models.overlay.OverlayResult` (overlay) objects whose
    ``wall_seconds`` is the FLEET wall clock — a lane's own
    ``*_per_second`` therefore reads as "if I had run alone at fleet
    cost"; the aggregate properties below are the fleet's throughput.
    """

    lanes: list
    wall_seconds: float

    @property
    def batch(self) -> int:
        return len(self.lanes)

    @property
    def total_node_ticks(self) -> int:
        return sum(r.cfg.n * r.ticks_run for r in self.lanes)

    @property
    def aggregate_node_ticks_per_second(self) -> float:
        if self.wall_seconds <= 0.0:
            return 0.0
        return self.total_node_ticks / self.wall_seconds

    @property
    def node_ticks_per_second_per_run(self) -> float:
        return self.aggregate_node_ticks_per_second / max(self.batch, 1)


class FleetSimulation:
    """Run B same-shape simulations through one compiled program.

    Construct with the fleet's config shape, then call :meth:`run`
    (trace mode / overlay metrics mode) or :meth:`run_bench` (dense
    bench mode) with either ``seeds=[...]`` (the common case: distinct
    seeds of ``cfg``) or ``configs=[...]`` (same-shape configs — e.g.
    the grader's three course scenarios, whose differences are all
    Schedule data).  Compiled fleet programs are cached per (mode,
    batch width, chunk length) on the instance; ``make_tick`` builds
    are shared process-wide as usual.

    The vmapped paths force the pure-XLA tick (``use_pallas=False``):
    vmap-of-``pallas_call`` is never sound here, and the TPU fleet
    answer is the grid kernel's explicit batch grid dimension
    (models/overlay_grid.make_grid_fleet_run), which
    :func:`~..models.overlay.make_overlay_fleet_run` selects on TPU.
    """

    def __init__(self, cfg: SimConfig, block_size: int = 128,
                 chunk_ticks: Optional[int] = None):
        self.cfg = cfg
        self.block_size = block_size
        self.chunk_ticks = chunk_ticks
        self._fns: dict = {}

    # ---- lane validation -------------------------------------------
    def _lane_cfgs(self, seeds, configs) -> list[SimConfig]:
        if (seeds is None) == (configs is None):
            raise ValueError("pass exactly one of seeds= or configs=")
        if configs is None:
            configs = [self.cfg.replace(seed=int(s)) for s in seeds]
        configs = list(configs)
        if not configs:
            raise ValueError("empty fleet")
        key = fleet_shape_key(self.cfg)
        for c in configs:
            if fleet_shape_key(c) != key:
                raise ValueError(
                    f"lane config {c} does not share the fleet's "
                    f"compiled shape {key}; fleets batch same-shape "
                    "simulations only")
        return configs

    # ---- dense bench ------------------------------------------------
    def _dense_bench_fn(self, batch: int, width: int):
        key = ("bench", batch, width)
        if key not in self._fns:
            cfg_w = self.cfg.replace(max_nnb=width)
            tick = make_tick(cfg_w, self.block_size, use_pallas=False,
                             with_events=False)
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, 0),
                             out_axes=(WORLD_AXES, EVENT_AXES))
            total = self.cfg.total_ticks

            @partial(jax.jit, donate_argnums=(0,))
            def run(states: WorldState, scheds: Schedule):
                def step(carry, _):
                    carry, ev = vtick(carry, scheds)
                    return carry, (ev.sent, ev.recv)
                return jax.lax.scan(step, states, None, length=total)

            self._fns[key] = run
        return self._fns[key]

    def run_bench(self, seeds=None, configs=None,
                  warmup: bool = True) -> FleetResult:
        """Bench-mode fleet: whole runs on device, one shared timing.

        Mirrors ``Simulation.run_bench`` semantics per lane — always a
        tick-0 start, and when the config's schedule never starts
        peers past the static active bound the whole fleet executes on
        the corner width (core/dense_corner.py; the bound is
        config-derived, so every lane shares it).  Counters follow the
        same stream-width caveat (``SimResult.counter_stream_width``).
        """
        cfgs = self._lane_cfgs(seeds, configs)
        if self.cfg.model == "overlay":
            return self._overlay_fleet(cfgs, warmup)
        from .dense_corner import (_embed_state, active_bound,
                                   bench_stream_width)
        bounds = {active_bound(c) for c in cfgs}
        if len(bounds) != 1:
            raise ValueError(
                f"lanes disagree on the active corner bound {bounds}; "
                "a fleet compiles one width")
        a = bounds.pop()
        n = self.cfg.n
        total = self.cfg.total_ticks
        corner = 0 < a < n
        width = a if corner else n
        run = self._dense_bench_fn(len(cfgs), width)
        scheds = [make_schedule(c) for c in cfgs]
        if corner:
            lane_scheds = [Schedule(
                start_tick=s.start_tick[:a], fail_tick=s.fail_tick[:a],
                rejoin_tick=s.rejoin_tick[:a],
                drop_active=s.drop_active, drop_prob=s.drop_prob)
                for s in scheds]
        else:
            lane_scheds = scheds
        sscheds = stack_lanes(lane_scheds)
        cfg_w = self.cfg.replace(max_nnb=width)

        def fresh_states():
            # rebuilt per call: the fleet program donates its carry
            return _stack_states([init_state(cfg_w.replace(seed=c.seed))
                                  for c in cfgs])

        if warmup:                        # compile outside the timing
            f, _ = run(fresh_states(), sscheds)
            jax.block_until_ready(f.known)
        t0 = time.perf_counter()
        final, (sent, recv) = run(fresh_states(), sscheds)
        jax.block_until_ready(final.known)
        if int(np.asarray(final.tick)) != total:
            raise RuntimeError("fleet bench did not complete all ticks")
        wall = time.perf_counter() - t0
        # (T, B, width) counter stacks -> per-lane (N, T)
        sr = np.asarray(jnp.stack([sent, recv]))
        lanes = []
        for i, (c, s) in enumerate(zip(cfgs, scheds)):
            fs = _lane_state(final, i)
            if corner:
                fs = _embed_state(fs, n)
            cnt = np.zeros((2, total, n), np.int32)
            cnt[:, :, :width] = sr[:, :, i, :]
            lanes.append(SimResult(
                cfg=c,
                start_tick=np.asarray(s.start_tick),
                fail_tick=np.asarray(s.fail_tick),
                rejoin_tick=np.asarray(s.rejoin_tick),
                added=None, removed=None,
                sent=cnt[0].T.copy(), recv=cnt[1].T.copy(),
                final_state=fs,
                wall_seconds=wall,
                counter_stream_width=bench_stream_width(c),
            ))
        return FleetResult(lanes=lanes, wall_seconds=wall)

    # ---- dense trace -------------------------------------------------
    def _dense_trace_fn(self, batch: int, length: int):
        key = ("trace", batch, length)
        if key not in self._fns:
            tick = make_tick(self.cfg, self.block_size, use_pallas=False,
                             with_events=True)
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, 0),
                             out_axes=(WORLD_AXES, EVENT_AXES))

            @partial(jax.jit, donate_argnums=(0,))
            def run(states: WorldState, scheds: Schedule):
                def step(carry, _):
                    return vtick(carry, scheds)
                return jax.lax.scan(step, states, None, length=length)

            self._fns[key] = run
        return self._fns[key]

    def run(self, seeds=None, configs=None) -> FleetResult:
        """Trace-mode fleet (dense): full event masks for every lane.

        Chunked over ticks like ``Simulation.run`` (the per-chunk
        device budget is divided by B), with the sparse event staging
        done ONCE across the whole batch per chunk.  Overlay configs
        dispatch to the metrics-mode fleet (the overlay has no dense
        event masks by design).
        """
        cfgs = self._lane_cfgs(seeds, configs)
        if self.cfg.model == "overlay":
            return self._overlay_fleet(cfgs, warmup=True)
        b = len(cfgs)
        n = self.cfg.n
        total = self.cfg.total_ticks
        chunk = self.chunk_ticks
        if chunk is None:
            per_tick = 2 * n * n * b
            chunk = max(1, min(total, (1 << 30) // max(per_tick, 1)))
        scheds = [make_schedule(c) for c in cfgs]
        sscheds = stack_lanes(scheds)
        states = _stack_states([init_state(c) for c in cfgs])
        added, removed, sent, recv = [], [], [], []
        t0 = time.perf_counter()
        done = 0
        while done < total:
            length = min(chunk, total - done)
            run = self._dense_trace_fn(b, length)
            states, ev = run(states, sscheds)
            # one sparse compaction for the whole (length*B, N, N) stack
            nw = (n + 31) // 32
            cap = max(1 << 14, (2 * length * b * n * nw) // 16)
            a_h, r_h = _masks_to_host(ev.added.reshape(length * b, n, n),
                                      ev.removed.reshape(length * b, n, n),
                                      cap)
            added.append(a_h.reshape(length, b, n, n))
            removed.append(r_h.reshape(length, b, n, n))
            if n <= 8192:
                sr = np.asarray(jnp.stack([ev.sent, ev.recv])
                                .astype(jnp.int16)).astype(np.int32)
            else:
                sr = np.asarray(jnp.stack([ev.sent, ev.recv]))
            sent.append(sr[0])
            recv.append(sr[1])
            done += length
        if int(np.asarray(states.tick)) != total:
            raise RuntimeError("fleet trace did not complete all ticks")
        wall = time.perf_counter() - t0
        lanes = []
        for i, (c, s) in enumerate(zip(cfgs, scheds)):
            lanes.append(SimResult(
                cfg=c,
                start_tick=np.asarray(s.start_tick),
                fail_tick=np.asarray(s.fail_tick),
                rejoin_tick=np.asarray(s.rejoin_tick),
                added=np.concatenate([ch[:, i] for ch in added], 0),
                removed=np.concatenate([ch[:, i] for ch in removed], 0),
                sent=np.concatenate([ch[:, i] for ch in sent], 0).T.copy(),
                recv=np.concatenate([ch[:, i] for ch in recv], 0).T.copy(),
                final_state=_lane_state(states, i),
                wall_seconds=wall,
            ))
        return FleetResult(lanes=lanes, wall_seconds=wall)

    # ---- overlay (metrics mode) --------------------------------------
    def _overlay_fleet(self, cfgs: Sequence[SimConfig],
                       warmup: bool) -> FleetResult:
        from ..models.overlay import (OverlayResult, init_overlay_state,
                                      make_overlay_fleet_run,
                                      make_overlay_schedule)
        b = len(cfgs)
        total = self.cfg.total_ticks
        run = make_overlay_fleet_run(self.cfg, b)
        scheds = [make_overlay_schedule(c) for c in cfgs]
        sscheds = stack_lanes(scheds)

        def fresh_states():
            return _stack_states([init_overlay_state(c) for c in cfgs])

        if warmup:
            f, _ = run(fresh_states(), sscheds)
            jax.block_until_ready(f.ids)
        t0 = time.perf_counter()
        final, metrics = run(fresh_states(), sscheds)
        jax.block_until_ready(final.ids)
        if int(np.asarray(final.tick)) != total:
            raise RuntimeError("fleet overlay run did not complete")
        wall = time.perf_counter() - t0
        metrics_h = jax.tree.map(np.asarray, metrics)
        lanes = [OverlayResult(
            cfg=c, sched=scheds[i],
            final_state=_lane_state(final, i),
            metrics=jax.tree.map(lambda m, _i=i: m[_i], metrics_h),
            wall_seconds=wall,
        ) for i, c in enumerate(cfgs)]
        return FleetResult(lanes=lanes, wall_seconds=wall)
