"""Fleet-batched execution: one compiled program serves B simulations.

Every request to this framework is a (seed x scenario) simulation, and
until this module each one ran alone: ``Simulation`` compiles per
config shape and each ``run``/``run_bench`` call dispatches its own
whole-run program.  The kernels are op-*issue*-bound, not
bandwidth-bound (docs/PERF.md §3, §8) — at bench scale the machine
spends more time issuing per-tick ops and per-launch dispatches than
computing — so batching B independent runs into ONE compiled program
is the same microbatching lever every serving stack uses.  SWIM-style
membership runs are embarrassingly parallel across seeds: the batch
axis is exact, not approximate, and per-lane trajectories stay
bit-identical to sequential runs (tests/test_fleet.py).

Shape of the thing:

* **One program, B lanes.**  States and schedules are stacked on a
  leading batch axis; the tick function runs under ``jax.vmap`` inside
  one jitted ``lax.scan`` whose stacked carry is donated
  (``donate_argnums`` — the packed state planes are never copied
  between launches).  Seeds live in the Schedule arrays/PRNG keys, so
  one compiled program serves any fleet of the same config shape.
* **The clock is shared.**  Lanes tick in lockstep, so ``state.tick``
  stays an UNBATCHED scalar (``vmap`` ``in_axes=None``).  This is
  load-bearing: a batched clock would turn every clock-derived
  ``lax.cond`` (the overlay's SLOT_EPOCH re-slot pass) into a
  both-branches select — measured 16x extra re-slot work on CPU.
* **Batch-native kernels where vmap would destroy them.**  On TPU the
  overlay fleet rides the grid megakernel's explicit leading batch
  grid dimension (``grid = B x ticks x row-blocks``,
  ops/pallas/overlay_grid.py) — never ``jax.vmap``-of-``pallas_call``.
* **Trace mode stages events once per batch.**  The sparse
  device->host event encoding (core/sim._masks_to_host) runs over the
  whole (chunk*B, N, N) stack in one compaction pass.

Measured on this CPU-only image (docs/PERF.md §8): a B=8 fleet of
n=2048 overlay-churn seeds delivers ~3x the aggregate node-ticks/s of
8 sequential runs; the grader's three course scenarios run as a single
B=3 fleet (grader.grade_all_fleet).
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimConfig
from ..state import Schedule, WorldState, init_state, make_schedule
from .sim import SimResult, _masks_to_host
from .tick import TickEvents, make_tick

#: vmap axes of a stacked fleet: every lane carries its own arrays but
#: the CLOCK is shared (see module docstring), so ``tick`` is None
WORLD_AXES = WorldState(tick=None, in_group=0, own_hb=0, known=0, hb=0,
                        ts=0, gossip=0, joinreq=0, joinrep=0, rng=0)
EVENT_AXES = TickEvents(added=0, removed=0, sent=0, recv=0)

#: Schedule axes when every lane shares one drop plan: the per-lane
#: injection arrays stay batched (seeds move victims), but
#: ``drop_active``/``drop_prob`` ride UNBATCHED, exactly like the
#: clock.  Load-bearing the same way the shared clock is: the drop
#: draw sits under a ``lax.cond`` on ``drop_active[t]``
#: (ops/drop.py), and a batched predicate degrades it to a
#: both-branches select — the per-tick threefry draw then runs on
#: EVERY tick of a no-drop config instead of never (measured 2.6x
#: the whole vmapped dense tick at n=24).  Lanes that genuinely
#: disagree on the drop plan fall back to SCHED_AXES_BATCHED.
SCHED_AXES_SHARED_DROP = Schedule(start_tick=0, fail_tick=0,
                                  rejoin_tick=0, drop_active=None,
                                  drop_prob=None)
SCHED_AXES_BATCHED = Schedule(start_tick=0, fail_tick=0, rejoin_tick=0,
                              drop_active=0, drop_prob=0)


def _shared_drop(cfgs) -> bool:
    """May the fleet share one unbatched drop plan across lanes?"""
    c0 = cfgs[0]
    return all((c.drop_msg, c.drop_open_tick, c.drop_close_tick,
                c.msg_drop_prob)
               == (c0.drop_msg, c0.drop_open_tick, c0.drop_close_tick,
                   c0.msg_drop_prob) for c in cfgs[1:])


def _stack_scheds(scheds, shared_drop: bool):
    """Stack per-lane schedules; one shared drop plan when allowed."""
    if not shared_drop:
        return stack_lanes(scheds)
    return Schedule(
        start_tick=jnp.stack([s.start_tick for s in scheds]),
        fail_tick=jnp.stack([s.fail_tick for s in scheds]),
        rejoin_tick=jnp.stack([s.rejoin_tick for s in scheds]),
        drop_active=scheds[0].drop_active,
        drop_prob=scheds[0].drop_prob)


def stack_lanes(trees):
    """Stack same-shape pytrees on a new leading lane axis.

    Mismatched lanes are rejected up front with the offending lane and
    field named — ``jnp.stack`` (or worse, the vmapped program it
    feeds) would otherwise fail deep inside tracing with no hint of
    which request caused it.
    """
    trees = list(trees)
    paths0, treedef0 = jax.tree_util.tree_flatten_with_path(trees[0])
    for i, t in enumerate(trees[1:], start=1):
        paths, treedef = jax.tree_util.tree_flatten_with_path(t)
        if treedef != treedef0:
            raise ValueError(
                f"lane {i} has a different pytree structure than lane 0 "
                f"({treedef} != {treedef0}); fleets stack same-shape "
                "lanes only")
        for (p0, leaf0), (p, leaf) in zip(paths0, paths):
            s0 = jnp.shape(leaf0)
            s = jnp.shape(leaf)
            if s != s0:
                field = jax.tree_util.keystr(p)
                raise ValueError(
                    f"lane {i} field {field} has shape {s}, but lane 0 "
                    f"has {s0}; fleets stack same-shape lanes only "
                    "(check the lane's config: peer count and tick "
                    "count set these shapes)")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _stack_states(states):
    """Stack per-lane states, keeping the shared clock a scalar."""
    st = stack_lanes(states)
    return st.replace(tick=states[0].tick)


def _lane_state(states, i: int):
    """Per-lane view of a stacked state (shared scalar clock)."""
    return type(states)(**{
        f.name: (getattr(states, f.name) if f.name == "tick"
                 else getattr(states, f.name)[i])
        for f in dataclasses.fields(type(states))})


def fleet_shape_key(cfg: SimConfig):
    """The config bits ONE compiled fleet program bakes in.

    Two configs with equal keys may ride the same program: everything
    else (seeds, victim windows, drop probabilities/windows, start
    ramps) flows through the Schedule arrays as data.  The overlay
    model compiles far more of the config statically (kernel phase
    elision, closed-form schedule constants), so its lanes must agree
    on everything but the seed.
    """
    if cfg.model == "overlay":
        return ("overlay", cfg.replace(seed=0))
    return ("full_view", cfg.n, cfg.t_remove, cfg.total_ticks,
            cfg.rejoin_after is None)


def _shape_mismatch(fleet_cfg: SimConfig, lane_cfg: SimConfig) -> str:
    """Name the config fields that break a lane's shape compatibility.

    Listing ``field=lane_value != fleet_value`` per offending field
    turns "failed deep inside vmap" into an actionable message: the
    caller learns exactly which knob (peer count, tick count, a whole
    overlay field) to fix on which lane.
    """
    if lane_cfg.model != fleet_cfg.model:
        return (f"model={lane_cfg.model!r} != fleet "
                f"model={fleet_cfg.model!r}")
    if fleet_cfg.model == "overlay":
        # the overlay compiles ~the whole config statically, so every
        # non-seed field is shape-relevant
        names = [f.name for f in dataclasses.fields(SimConfig)
                 if f.name != "seed"]
    else:
        names = ["max_nnb", "t_remove", "total_ticks"]
    diffs = [f"{n}={getattr(lane_cfg, n)!r} != fleet "
             f"{n}={getattr(fleet_cfg, n)!r}"
             for n in names
             if getattr(lane_cfg, n) != getattr(fleet_cfg, n)]
    if fleet_cfg.model != "overlay" and \
            (lane_cfg.rejoin_after is None) != (fleet_cfg.rejoin_after is None):
        diffs.append(f"rejoin_after={lane_cfg.rejoin_after!r} != fleet "
                     f"rejoin_after={fleet_cfg.rejoin_after!r}")
    return ", ".join(diffs) or "(keys differ)"


#: Compiled fleet programs, shared across FleetSimulation instances
#: (exactly like core/tick._RUN_CACHE for single runs).  Keys carry
#: the fleet shape key, the segment-plan signature, the MESH slot
#: (None on the single-device path; the lane-mesh descriptor on
#: parallel/fleet_mesh.py's — a device-count change can never be
#: served a stale program), and the batch geometry; misses are
#: counted through core.tick.note_build so the serving layer's "one
#: build per distinct bucket key" contract is a run_build_count delta.
_FLEET_FN_CACHE: dict = {}


def _fleet_fn(key, builder):
    if key not in _FLEET_FN_CACHE:
        from .tick import note_build
        note_build()
        _FLEET_FN_CACHE[key] = builder()
    return _FLEET_FN_CACHE[key]


def _check_unstacked(lanes, n_real: int) -> None:
    """Filler-lane invariant, enforced at the unstack boundary: a
    fleet hands back EXACTLY its real lanes — one per request, filler
    never among them.  The serving layer zips lanes against requests,
    so a miscount here would silently mispair results (or strand
    handles); failing loudly turns it into an ordinary retryable
    dispatch error (service/resilience.py)."""
    if len(lanes) != n_real:
        raise RuntimeError(
            f"fleet unstacked {len(lanes)} lanes but n_real={n_real}; "
            "filler lanes must never be unstacked into results")


@dataclass
class FleetResult:
    """A finished fleet: per-lane results plus the one shared wall.

    ``lanes`` hold :class:`~..core.sim.SimResult` (dense model) or
    :class:`~..models.overlay.OverlayResult` (overlay) objects whose
    ``wall_seconds`` is the FLEET wall clock — a lane's own
    ``*_per_second`` therefore reads as "if I had run alone at fleet
    cost"; the aggregate properties below are the fleet's throughput.

    When the program executed with trailing filler lanes (a partial
    service batch padded to the compiled width, ``n_real=`` on
    :meth:`FleetSimulation.run`/:meth:`~FleetSimulation.run_bench`),
    ``lanes`` holds only the REAL lanes — filler results are never
    unstacked — and ``padded_batch``/``occupancy`` record the padding.
    """

    lanes: list
    wall_seconds: float
    #: compiled batch width actually dispatched (>= len(lanes) when
    #: filler lanes padded a partial batch; 0 = no padding happened)
    padded_batch: int = 0
    #: seconds of ``wall_seconds`` spent waiting on the device program
    #: (dispatch + block_until_ready); the remainder is host-side
    #: stack/unstack work.  The serving layer splits its per-dispatch
    #: wall on this so mesh speedups land in the right column
    #: (FleetService.stats).
    device_seconds: float = 0.0

    @property
    def batch(self) -> int:
        return len(self.lanes)

    @property
    def occupancy(self) -> float:
        """Real-lane fraction of the dispatched program (1.0 unpadded)."""
        width = self.padded_batch or self.batch
        return self.batch / width if width else 0.0

    @property
    def total_node_ticks(self) -> int:
        return sum(r.cfg.n * r.ticks_run for r in self.lanes)

    @property
    def aggregate_node_ticks_per_second(self) -> float:
        if self.wall_seconds <= 0.0:
            return 0.0
        return self.total_node_ticks / self.wall_seconds

    @property
    def node_ticks_per_second_per_run(self) -> float:
        return self.aggregate_node_ticks_per_second / max(self.batch, 1)


class FleetSimulation:
    """Run B same-shape simulations through one compiled program.

    Construct with the fleet's config shape, then call :meth:`run`
    (trace mode / overlay metrics mode) or :meth:`run_bench` (dense
    bench mode) with either ``seeds=[...]`` (the common case: distinct
    seeds of ``cfg``) or ``configs=[...]`` (same-shape configs — e.g.
    the grader's three course scenarios, whose differences are all
    Schedule data).  Compiled fleet programs are cached process-wide
    (``_FLEET_FN_CACHE``) per (shape key, segment-plan signature,
    mode, batch width, chunk length), so every FleetSimulation of the
    same shape shares one build — the serving layer
    (service/cache.py) leans on this for its one-build-per-bucket
    contract.

    ``n_real=k`` marks the trailing ``B - k`` lanes as FILLER: a
    partial batch padded up to an already-compiled width.  Filler
    lanes execute like any other lane but are masked out of the
    host-side result path — their events never enter the sparse
    device->host compaction (they cannot inflate its budget or flip
    it to the dense fallback) and they are never unstacked into
    ``FleetResult.lanes``.  vmap lanes are data-independent by
    construction (the only shared carry is the unbatched clock, which
    every lane advances identically), so filler cannot perturb real
    lanes' results — pinned bit-for-bit by
    tests/test_service.py::test_padding_parity.

    The vmapped paths force the pure-XLA tick (``use_pallas=False``):
    vmap-of-``pallas_call`` is never sound here, and the TPU fleet
    answer is the grid kernel's explicit batch grid dimension
    (models/overlay_grid.make_grid_fleet_run), which
    :func:`~..models.overlay.make_overlay_fleet_run` selects on TPU.
    """

    def __init__(self, cfg: SimConfig, block_size: int = 128,
                 chunk_ticks: Optional[int] = None):
        self.cfg = cfg
        self.block_size = block_size
        self.chunk_ticks = chunk_ticks
        # every _FLEET_FN_CACHE key this instance touched, so
        # evict_programs() drops exactly this bucket's programs — a
        # prefix match would also hit sibling buckets that share the
        # shape but differ in mode or drop probability
        self._program_keys: set = set()

    def _fleet_program(self, key, builder):
        self._program_keys.add(key)
        return _fleet_fn(key, builder)

    @staticmethod
    def _resolve_n_real(batch: int, n_real) -> int:
        if n_real is None:
            return batch
        if not 1 <= n_real <= batch:
            raise ValueError(
                f"n_real={n_real} must be in [1, {batch}] (the fleet "
                f"dispatched {batch} lanes; filler lanes are the "
                "trailing ones)")
        return int(n_real)

    # ---- lane validation -------------------------------------------
    def _lane_cfgs(self, seeds, configs) -> list[SimConfig]:
        if (seeds is None) == (configs is None):
            raise ValueError("pass exactly one of seeds= or configs=")
        if configs is None:
            configs = [self.cfg.replace(seed=int(s)) for s in seeds]
        configs = list(configs)
        if not configs:
            raise ValueError("empty fleet")
        key = fleet_shape_key(self.cfg)
        for i, c in enumerate(configs):
            if fleet_shape_key(c) != key:
                raise ValueError(
                    f"lane {i} does not share the fleet's compiled "
                    f"shape: {_shape_mismatch(self.cfg, c)}; fleets "
                    "batch same-shape simulations only")
        return configs

    # ---- shared program cache ---------------------------------------
    def _mesh_entry(self):
        """The mesh slot of this fleet's program-cache keys.

        ``None`` here (single-device); parallel/fleet_mesh.py overrides
        with the lane-mesh descriptor, so a device-count change is a
        different key — never a stale program.
        """
        return None

    def _key_prefix(self) -> tuple:
        from ..models.segments import plan_signature
        return (fleet_shape_key(self.cfg), plan_signature(self.cfg),
                self.block_size, self._mesh_entry())

    def _cache_key(self, *extra):
        return self._key_prefix() + extra

    def evict_programs(self) -> int:
        """Drop this handle's compiled programs from the process
        caches; returns how many were evicted.

        The serving layer's bounded ProgramCache calls this on LRU
        eviction so dropping a bucket handle actually frees its jitted
        executables rather than just the thin FleetSimulation wrapper.
        ``_FLEET_FN_CACHE`` eviction is exact — only keys THIS
        instance touched, so a sibling bucket sharing the shape (other
        mode, other drop probability) keeps its programs.  The
        single-device overlay path compiles through
        ``_OVERLAY_FLEET_CACHE`` instead, whose keys this class cannot
        enumerate per-instance; those are purged by seed-stripped
        config, which may also evict a mode-sibling overlay bucket of
        the identical config — one redundant rebuild, never a
        correctness issue.
        """
        n = 0
        for k in self._program_keys:
            if _FLEET_FN_CACHE.pop(k, None) is not None:
                n += 1
        self._program_keys.clear()
        if self.cfg.model == "overlay" and self._mesh_entry() is None:
            from ..models.overlay import _OVERLAY_FLEET_CACHE
            shape = self.cfg.replace(seed=0)
            stale = [k for k in _OVERLAY_FLEET_CACHE if k[0] == shape]
            for k in stale:
                del _OVERLAY_FLEET_CACHE[k]
            n += len(stale)
        return n

    # ---- dense bench ------------------------------------------------
    def _dense_bench_fn(self, batch: int, width: int, shared_drop: bool):
        def build():
            cfg_w = self.cfg.replace(max_nnb=width)
            tick = make_tick(cfg_w, self.block_size, use_pallas=False,
                             with_events=False)
            axes = SCHED_AXES_SHARED_DROP if shared_drop \
                else SCHED_AXES_BATCHED
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                             out_axes=(WORLD_AXES, EVENT_AXES))
            total = self.cfg.total_ticks

            @partial(jax.jit, donate_argnums=(0,))
            def run(states: WorldState, scheds: Schedule):
                def step(carry, _):
                    carry, ev = vtick(carry, scheds)
                    return carry, (ev.sent, ev.recv)
                return jax.lax.scan(step, states, None, length=total)

            return run

        return self._fleet_program(self._cache_key("bench", batch, width,
                                         shared_drop), build)

    def run_bench(self, seeds=None, configs=None, warmup: bool = True,
                  n_real: Optional[int] = None) -> FleetResult:
        """Bench-mode fleet: whole runs on device, one shared timing.

        Mirrors ``Simulation.run_bench`` semantics per lane — always a
        tick-0 start, and when the config's schedule never starts
        peers past the static active bound the whole fleet executes on
        the corner width (core/dense_corner.py; the bound is
        config-derived, so every lane shares it).  Counters follow the
        same stream-width caveat (``SimResult.counter_stream_width``).
        ``n_real`` marks trailing lanes as filler (see class docs).
        """
        cfgs = self._lane_cfgs(seeds, configs)
        nr = self._resolve_n_real(len(cfgs), n_real)
        if self.cfg.model == "overlay":
            return self._overlay_fleet(cfgs, warmup, nr)
        from .dense_corner import (_embed_state, active_bound,
                                   bench_stream_width)
        bounds = {active_bound(c) for c in cfgs}
        if len(bounds) != 1:
            raise ValueError(
                f"lanes disagree on the active corner bound {bounds}; "
                "a fleet compiles one width")
        a = bounds.pop()
        n = self.cfg.n
        total = self.cfg.total_ticks
        corner = 0 < a < n
        width = a if corner else n
        shared = _shared_drop(cfgs)
        run = self._dense_bench_fn(len(cfgs), width, shared)
        scheds = [make_schedule(c) for c in cfgs]
        if corner:
            lane_scheds = [Schedule(
                start_tick=s.start_tick[:a], fail_tick=s.fail_tick[:a],
                rejoin_tick=s.rejoin_tick[:a],
                drop_active=s.drop_active, drop_prob=s.drop_prob)
                for s in scheds]
        else:
            lane_scheds = scheds
        sscheds = _stack_scheds(lane_scheds, shared)
        cfg_w = self.cfg.replace(max_nnb=width)

        def fresh_states():
            # rebuilt per call: the fleet program donates its carry
            return _stack_states([init_state(cfg_w.replace(seed=c.seed))
                                  for c in cfgs])

        if warmup:                        # compile outside the timing
            f, _ = run(fresh_states(), sscheds)
            jax.block_until_ready(f.known)
        t0 = time.perf_counter()
        states0 = fresh_states()
        t_dev0 = time.perf_counter()
        final, (sent, recv) = run(states0, sscheds)
        jax.block_until_ready(final.known)
        t_dev = time.perf_counter() - t_dev0
        if int(np.asarray(final.tick)) != total:
            raise RuntimeError("fleet bench did not complete all ticks")
        wall = time.perf_counter() - t0
        # (T, B, width) counter stacks -> per-lane (N, T); filler
        # lanes' counters are sliced away before they reach the host
        sr = np.asarray(jnp.stack([sent, recv])[:, :, :nr])
        lanes = []
        for i, (c, s) in enumerate(zip(cfgs[:nr], scheds[:nr])):
            fs = _lane_state(final, i)
            if corner:
                fs = _embed_state(fs, n)
            cnt = np.zeros((2, total, n), np.int32)
            cnt[:, :, :width] = sr[:, :, i, :]
            lanes.append(SimResult(
                cfg=c,
                start_tick=np.asarray(s.start_tick),
                fail_tick=np.asarray(s.fail_tick),
                rejoin_tick=np.asarray(s.rejoin_tick),
                added=None, removed=None,
                sent=cnt[0].T.copy(), recv=cnt[1].T.copy(),
                final_state=fs,
                wall_seconds=wall,
                counter_stream_width=bench_stream_width(c),
            ))
        _check_unstacked(lanes, nr)
        return FleetResult(lanes=lanes, wall_seconds=wall,
                           padded_batch=len(cfgs) if nr < len(cfgs) else 0,
                           device_seconds=t_dev)

    # ---- dense trace -------------------------------------------------
    def _dense_trace_fn(self, batch: int, length: int, shared_drop: bool):
        def build():
            tick = make_tick(self.cfg, self.block_size, use_pallas=False,
                             with_events=True)
            axes = SCHED_AXES_SHARED_DROP if shared_drop \
                else SCHED_AXES_BATCHED
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                             out_axes=(WORLD_AXES, EVENT_AXES))

            @partial(jax.jit, donate_argnums=(0,))
            def run(states: WorldState, scheds: Schedule):
                def step(carry, _):
                    return vtick(carry, scheds)
                return jax.lax.scan(step, states, None, length=length)

            return run

        return self._fleet_program(self._cache_key("trace", batch, length,
                                         shared_drop), build)

    def run(self, seeds=None, configs=None, n_real: Optional[int] = None,
            warmup: bool = True) -> FleetResult:
        """Trace-mode fleet (dense): full event masks for every lane.

        Chunked over ticks like ``Simulation.run`` (the per-chunk
        device budget is divided by B), with the sparse event staging
        done ONCE across the whole batch per chunk.  Overlay configs
        dispatch to the metrics-mode fleet (the overlay has no dense
        event masks by design); ``warmup`` only affects that path —
        the service scheduler passes ``False`` so a dispatch never
        executes its fleet twice just to exclude compile time from
        ``wall_seconds``.  ``n_real`` marks trailing lanes as filler:
        they run on device but are masked out of the event staging and
        result unstacking entirely (see class docs).
        """
        cfgs = self._lane_cfgs(seeds, configs)
        nr = self._resolve_n_real(len(cfgs), n_real)
        if self.cfg.model == "overlay":
            return self._overlay_fleet(cfgs, warmup=warmup, n_real=nr)
        b = len(cfgs)
        n = self.cfg.n
        total = self.cfg.total_ticks
        chunk = self.chunk_ticks
        if chunk is None:
            per_tick = 2 * n * n * b
            chunk = max(1, min(total, (1 << 30) // max(per_tick, 1)))
        shared = _shared_drop(cfgs)
        scheds = [make_schedule(c) for c in cfgs]
        sscheds = _stack_scheds(scheds, shared)
        states = _stack_states([init_state(c) for c in cfgs])
        added, removed, sent, recv = [], [], [], []
        t0 = time.perf_counter()
        t_dev = 0.0
        done = 0
        while done < total:
            length = min(chunk, total - done)
            run = self._dense_trace_fn(b, length, shared)
            t_dev0 = time.perf_counter()
            states, ev = run(states, sscheds)
            jax.block_until_ready(states.tick)
            t_dev += time.perf_counter() - t_dev0
            # one sparse compaction for the whole (length*n_real, N, N)
            # stack — filler lanes are sliced off ON DEVICE first, so
            # their events can neither inflate the sparse budget nor
            # tip the transfer into the dense fallback
            nw = (n + 31) // 32
            cap = max(1 << 14, (2 * length * nr * n * nw) // 16)
            a_h, r_h = _masks_to_host(
                ev.added[:, :nr].reshape(length * nr, n, n),
                ev.removed[:, :nr].reshape(length * nr, n, n), cap)
            added.append(a_h.reshape(length, nr, n, n))
            removed.append(r_h.reshape(length, nr, n, n))
            if n <= 8192:
                sr = np.asarray(jnp.stack([ev.sent, ev.recv])[:, :, :nr]
                                .astype(jnp.int16)).astype(np.int32)
            else:
                sr = np.asarray(jnp.stack([ev.sent, ev.recv])[:, :, :nr])
            sent.append(sr[0])
            recv.append(sr[1])
            done += length
        if int(np.asarray(states.tick)) != total:
            raise RuntimeError("fleet trace did not complete all ticks")
        wall = time.perf_counter() - t0
        lanes = []
        for i, (c, s) in enumerate(zip(cfgs[:nr], scheds[:nr])):
            lanes.append(SimResult(
                cfg=c,
                start_tick=np.asarray(s.start_tick),
                fail_tick=np.asarray(s.fail_tick),
                rejoin_tick=np.asarray(s.rejoin_tick),
                added=np.concatenate([ch[:, i] for ch in added], 0),
                removed=np.concatenate([ch[:, i] for ch in removed], 0),
                sent=np.concatenate([ch[:, i] for ch in sent], 0).T.copy(),
                recv=np.concatenate([ch[:, i] for ch in recv], 0).T.copy(),
                final_state=_lane_state(states, i),
                wall_seconds=wall,
            ))
        _check_unstacked(lanes, nr)
        return FleetResult(lanes=lanes, wall_seconds=wall,
                           padded_batch=b if nr < b else 0,
                           device_seconds=t_dev)

    def _overlay_fleet_fn(self, batch: int):
        """The overlay fleet's compiled program (the mesh subclass in
        parallel/fleet_mesh.py overrides this with the lane-sharded
        build)."""
        from ..models.overlay import make_overlay_fleet_run
        return make_overlay_fleet_run(self.cfg, batch)

    # ---- overlay (metrics mode) --------------------------------------
    def _overlay_fleet(self, cfgs: Sequence[SimConfig], warmup: bool,
                       n_real: Optional[int] = None) -> FleetResult:
        from ..models.overlay import (OverlayResult, init_overlay_state,
                                      make_overlay_schedule)
        b = len(cfgs)
        nr = self._resolve_n_real(b, n_real)
        total = self.cfg.total_ticks
        run = self._overlay_fleet_fn(b)
        scheds = [make_overlay_schedule(c) for c in cfgs]
        sscheds = stack_lanes(scheds)

        def fresh_states():
            return _stack_states([init_overlay_state(c) for c in cfgs])

        if warmup:
            f, _ = run(fresh_states(), sscheds)
            jax.block_until_ready(f.ids)
        t0 = time.perf_counter()
        states0 = fresh_states()
        t_dev0 = time.perf_counter()
        final, metrics = run(states0, sscheds)
        jax.block_until_ready(final.ids)
        t_dev = time.perf_counter() - t_dev0
        if int(np.asarray(final.tick)) != total:
            raise RuntimeError("fleet overlay run did not complete")
        wall = time.perf_counter() - t0
        # filler lanes are dropped on device before the (B, T) metric
        # stacks cross to host
        metrics_h = jax.tree.map(lambda m: np.asarray(m[:nr]), metrics)
        lanes = [OverlayResult(
            cfg=c, sched=scheds[i],
            final_state=_lane_state(final, i),
            metrics=jax.tree.map(lambda m, _i=i: m[_i], metrics_h),
            wall_seconds=wall,
        ) for i, c in enumerate(cfgs[:nr])]
        _check_unstacked(lanes, nr)
        return FleetResult(lanes=lanes, wall_seconds=wall,
                           padded_batch=b if nr < b else 0,
                           device_seconds=t_dev)
