"""Static active-corner reduction for the dense full-view model.

The reference introduces peer ``i`` at tick ``STEP_RATE * i``
(Application.cpp:143) and its driver loops only over nodes that have
been started (Application.cpp:138-163) — a 4096-peer run of 200 ticks
touches ~800 nodes, and the C++ cost scales with the *started* count,
not the configured one.  The batched tick (core/tick.py) as written
pays the full (N, N) planes every tick regardless.

Because start ticks are nondecreasing in the peer index, the set of
peers that can ever act within a run is the contiguous prefix
``[0, A)`` with ``A = min{i : start_tick(i) >= total_ticks}`` — a
*static* bound derived from the config alone.  Peers outside it never
start, never process, never send, and no entry for them is ever
created (entries for ``j`` only arise from ``j``'s own messages), so
every state row/column ``>= A`` is identically zero for the whole run
(asserted by tests/test_dense_corner.py).  The run can therefore
execute on the leading ``A x A`` corner of the planes and embed the
result back — bit-identical, with the matmul work down by
``(N / A)^3`` and the drop draw by ``(N / A)^2``.

The drop stream is drawn at the corner width (``tick_drop_masks`` with
``n = A``): mask bits outside the corner are dead (no send ever leaves
it), and the full-width tick accepts ``n_active=A`` to consume the
byte-identical stream for the differential tests.  Every *other* path
(trace mode, sharded, dense mega at full width) draws at width N — so
for a drop config with ``A < N`` the corner consumes a different,
equally seeded realization of the same Bernoulli process.  For
configs where every peer starts (``A == N`` — all grader testcases,
the 512-peer bench family, every cross-path differential pairing in
the suite) the streams coincide and all paths stay bit-identical.

Bench mode only (``with_events=False``), and only for whole runs: the
trace path materializes (T, N, N) event masks whose embedding would
dominate, and ``Simulation.run`` compiles per-*chunk* runs whose
``total_ticks`` is the chunk length — a chunk-derived bound would be
wrong for later chunks' absolute ticks, so ``make_run`` never routes
chunked runs here (``active_bound`` is meaningful only against the
full horizon).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import SimConfig
from ..state import Schedule, WorldState


def active_bound(cfg: SimConfig) -> int:
    """Smallest peer count that covers every peer that can ever act.

    Two ways a peer enters the world: its scheduled start
    (``start_tick(i) < total_ticks``; start ticks are monotone, so the
    cutoff index is found by bisection) and — under the churn
    extension — a scheduled *rejoin*, which re-runs nodeStart for the
    victim regardless of its start tick (core/tick.py ``starting``).
    Victims are drawn from the run seed, and the bound must stay
    seed-independent (``make_run``/``Simulation`` cache one compiled
    run per config and reseed it through the Schedule arrays alone),
    so a config whose rejoin can fire inside the run gets no corner
    at all.  The bound is padded up to a multiple of 128 so the
    corner keeps the tile divisibility of the fused kernels, and
    capped at N.
    """
    n, total = cfg.n, cfg.total_ticks
    if cfg.has_worlds:
        # adversarial worlds (worlds.py) fail/flap/partition hashed
        # node sets drawn from the run seed — the corner must stay
        # seed-independent, so world configs run full width
        return n
    if cfg.step_rate < 0:
        # the bisection requires start_tick(i) nondecreasing in i; a
        # negative step_rate (the field is an unvalidated float) breaks
        # that, so fall back to the full width instead of miscomputing
        # the corner (ADVICE round 5, item 2)
        return n
    if (cfg.rejoin_after is not None
            and cfg.fail_tick + cfg.rejoin_after < total):
        return n
    if total > 0 and cfg.start_tick(n - 1) < total:
        return n
    lo, hi = 0, n - 1          # invariant: start_tick(hi) >= total
    while lo < hi:
        mid = (lo + hi) // 2
        if cfg.start_tick(mid) >= total:
            hi = mid
        else:
            lo = mid + 1
    return min(n, -(-lo // 128) * 128)


def bench_stream_width(cfg: SimConfig) -> int:
    """Width at which a bench-mode run draws its drop stream.

    Mirrors ``make_run``'s corner routing: the corner path draws at
    width ``A = active_bound(cfg)``, every other path at ``N``.  For a
    drop config with ``A < N`` the bench counters therefore consume a
    *different, equally seeded* realization of the drop process than a
    trace-mode run of the same seed (see the module docstring) —
    ``SimResult.counter_stream_width`` carries this value so
    downstream tooling can detect when bench and trace counters are
    not bit-comparable (ADVICE round 5, item 3).
    """
    a = active_bound(cfg)
    return a if 0 < a < cfg.n else cfg.n


def _slice_state(state: WorldState, a: int) -> WorldState:
    return WorldState(
        tick=state.tick, rng=state.rng,
        in_group=state.in_group[:a], own_hb=state.own_hb[:a],
        known=state.known[:a, :a], hb=state.hb[:a, :a],
        ts=state.ts[:a, :a], gossip=state.gossip[:a, :a],
        gossip_age=state.gossip_age[:a, :a],
        joinreq=state.joinreq[:a], joinrep=state.joinrep[:a])


def _embed_state(state_a: WorldState, n: int) -> WorldState:
    a = state_a.known.shape[0]

    def vec(v):
        return jnp.zeros((n,), v.dtype).at[:a].set(v)

    def plane(p):
        return jnp.zeros((n, n), p.dtype).at[:a, :a].set(p)

    return WorldState(
        tick=state_a.tick, rng=state_a.rng,
        in_group=vec(state_a.in_group), own_hb=vec(state_a.own_hb),
        known=plane(state_a.known), hb=plane(state_a.hb),
        ts=plane(state_a.ts), gossip=plane(state_a.gossip),
        gossip_age=plane(state_a.gossip_age),
        joinreq=vec(state_a.joinreq), joinrep=vec(state_a.joinrep))


def make_corner_run(cfg: SimConfig, a: int, block_size: int = 128,
                    use_pallas: bool | None = None,
                    force_mega: bool | None = None):
    """Bench-mode whole-run function on the ``a x a`` active corner.

    Same contract as ``make_run(cfg, with_events=False)``: a
    ``run(state, sched) -> (final_state, TickEvents)`` over full-width
    arrays; internally the scan runs at width ``a``.  When the corner
    fits the dense megakernel envelope the launches ride it (the
    BASELINE N=4096 / 200-tick shape has A = 896; a corner of <= 512
    arises for longer-N, shorter-T points).

    ``active_bound`` is computed against the run's *absolute* tick
    horizon, so the corner is only valid for runs that begin at tick 0
    — the returned run raises otherwise (ADVICE round 5, item 1;
    ``Simulation.run_bench`` always starts from ``init_state``).

    ``force_mega`` overrides the megakernel auto-selection (None).
    Forcing it on a non-TPU backend runs the megakernel in interpret
    mode with eager launches — the CI differential path for the
    corner+mega combination (tests/test_dense_fuzz.py), which
    otherwise only executes on hardware.
    """
    from ..parallel.comm import LocalComm
    from .dense_mega import dense_mega_supported, make_dense_mega_run
    from .tick import TickEvents, make_tick

    n = cfg.n
    assert 0 < a < n and a % 8 == 0
    cfg_a = cfg.replace(max_nnb=a)
    comm = LocalComm(use_pallas)
    on_tpu = jax.default_backend() == "tpu"
    mega = (comm.use_pallas and dense_mega_supported(cfg_a) and on_tpu) \
        if force_mega is None else force_mega
    if mega:
        assert dense_mega_supported(cfg_a), (a, cfg_a.n)
        inner = make_dense_mega_run(cfg_a, with_events=False,
                                    as_body=on_tpu)
    else:
        tick = make_tick(cfg_a, block_size, use_pallas=comm.use_pallas,
                         with_events=False)

        def inner(state_a, sched_a):
            def step(carry, _):
                carry, ev = tick(carry, sched_a)
                return carry, (ev.sent, ev.recv)
            final_a, (sent, recv) = jax.lax.scan(
                step, state_a, None, length=cfg.total_ticks)
            # bench-mode event placeholders are (T,)-shaped on every
            # make_run path (scan-stacked scalars / mega's zeros)
            ev = TickEvents(added=jnp.zeros((cfg.total_ticks,), bool),
                            removed=jnp.zeros((cfg.total_ticks,), bool),
                            sent=sent, recv=recv)
            return final_a, ev

    def run_body(state: WorldState, sched: Schedule):
        from ..state import slice_schedule
        sched_a = slice_schedule(sched, a)
        final_a, ev = inner(_slice_state(state, a), sched_a)
        pad = ((0, 0), (0, n - a))
        ev = TickEvents(added=ev.added, removed=ev.removed,
                        sent=jnp.pad(ev.sent, pad),
                        recv=jnp.pad(ev.recv, pad))
        return _embed_state(final_a, n), ev

    def _check_clock(state: WorldState):
        tick = state.tick
        if isinstance(tick, jax.core.Tracer):
            # the corner's validity depends on the absolute clock —
            # refuse an unverifiable (traced) one rather than risk a
            # silently wrong corner on a resumed state
            raise ValueError(
                "active-corner run cannot verify its tick-0 "
                "precondition under a traced state; call it outside "
                "jit (Simulation.run_bench does)")
        if int(tick) != 0:
            raise ValueError(
                f"active-corner run requires a tick-0 start (the bound "
                f"spans the whole {cfg.total_ticks}-tick horizon), got "
                f"tick {int(tick)}")

    if on_tpu:
        # same raised scoped-VMEM window as make_dense_mega_run: the
        # megakernel (and the fused epilogue at larger corners) runs
        # inlined under this jit
        inner_run = jax.jit(run_body, compiler_options={
            "xla_tpu_scoped_vmem_limit_kib": "114688"})
    elif mega:
        # forced interpret-mode megakernel: eager launches (inlining
        # interpret kernels under jit blows up the XLA:CPU compile)
        inner_run = run_body
    else:
        inner_run = jax.jit(run_body)

    def run(state: WorldState, sched: Schedule):
        _check_clock(state)
        return inner_run(state, sched)

    return run
