"""Host harness for the dense full-view megakernel (bench mode).

Packs :class:`~..state.WorldState` plus the schedule columns into the
dense megakernel's planes (ops/pallas/dense_mega.py), precomputes each
launch's drop masks with the exact ops/drop.py streams, and runs
whole-``DENSE_MEGA_TICKS`` launches.  Returns the same
``(final_state, TickEvents)`` contract as ``make_run(...,
with_events=False)`` — a drop-in for ``Simulation.run_bench`` —
and is bit-identical to the per-tick XLA path
(tests/test_dense_mega.py).

On TPU the launches run inside one jitted ``lax.scan``; on other
backends each launch dispatches eagerly (same rationale as
models/overlay_mega.py: inlining interpret-mode kernels into an outer
jitted scan blows up the XLA:CPU compile).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import SimConfig
from ..ops.drop import tick_drop_masks
from ..ops.pallas.dense_mega import (DENSE_MEGA_N_LIMIT,
                                     DENSE_MEGA_N_LIMIT_BENCH,
                                     dense_mega_ticks,
                                     dense_mega_ticks_for)
from ..state import Schedule, WorldState


def dense_mega_supported(cfg: SimConfig, with_events: bool = False) -> bool:
    """Dense megakernel envelope (single device).  Trace mode carries
    two extra (S, N, N) event planes in VMEM, so its envelope is
    smaller than bench mode's."""
    limit = DENSE_MEGA_N_LIMIT if with_events else DENSE_MEGA_N_LIMIT_BENCH
    # the adversarial worlds (worlds.py) are not compiled into the
    # megakernel — except the WAVE, which is pure schedule data (it
    # only rewrites the fail_tick array the kernel already consumes);
    # zombie/partition/asym/flap change tick semantics and take the
    # XLA per-tick path
    non_schedule_worlds = any(w[0] != "wave" for w in cfg.worlds_key())
    return 16 <= cfg.n <= limit and cfg.n % 8 == 0 \
        and not non_schedule_worlds


def make_dense_mega_run(cfg: SimConfig, with_events: bool = False,
                        as_body: bool = False):
    """``run(state, sched) -> (final, TickEvents)`` over the whole run.

    ``with_events=False`` is bench mode (sent/recv counters only);
    ``with_events=True`` also returns the full (T, N, N) added/removed
    masks, emitted per tick by the kernel itself — the graded
    trace-mode path rides the same megakernel.  ``as_body`` returns
    the unjitted TPU body for inlining under a caller's jit (the
    corner run, core/dense_corner.py) — TPU only, the caller must
    raise the scoped-VMEM window itself."""
    from .tick import TickEvents
    assert dense_mega_supported(cfg, with_events)
    n = cfg.n
    total = cfg.total_ticks
    s_full = dense_mega_ticks_for(n)
    n_chunks, rem = divmod(total, s_full)
    can_rejoin = cfg.rejoin_after is not None
    kern_kw = dict(n=n, t_remove=cfg.t_remove, can_rejoin=can_rejoin,
                   with_events=with_events)

    def drop_stack(rng, t0, s_ticks, sched: Schedule):
        ts = t0 + jnp.arange(s_ticks, dtype=jnp.int32)
        g, q, p = jax.vmap(
            lambda t: tick_drop_masks(rng, t, n, sched.drop_active[t],
                                      sched.drop_prob))(ts)
        return g, q, p              # (S, N, N), (S, N), (S, N)

    def pack(state: WorldState, sched: Schedule):
        i32 = jnp.int32
        aux = jnp.stack([
            state.in_group.astype(i32), state.own_hb,
            state.joinreq.astype(i32), state.joinrep.astype(i32),
            sched.start_tick, sched.fail_tick, sched.rejoin_tick,
            jnp.zeros((n,), i32)], axis=1)                 # (N, 8)
        return (state.known.astype(i32), state.hb, state.ts,
                state.gossip.astype(i32), aux)

    def unpack(planes, aux, tick, rng) -> WorldState:
        known, hb, ts, gossip = planes
        # the mega envelope excludes the latency plane (make_run gates
        # on worlds_key), so the age plane is identically zero here
        return WorldState(
            tick=tick.astype(jnp.int32), in_group=aux[:, 0] > 0,
            own_hb=aux[:, 1], known=known > 0, hb=hb, ts=ts,
            gossip=gossip > 0, gossip_age=jnp.zeros((n, n), jnp.int32),
            joinreq=aux[:, 2] > 0, joinrep=aux[:, 3] > 0, rng=rng)

    def launch(planes, aux, t, state_rng, sched, s_ticks):
        g, q, p = drop_stack(state_rng, t, s_ticks, sched)
        sp = jnp.reshape(t, (1,)).astype(jnp.int32)
        known, hb, ts, gossip = planes
        out = dense_mega_ticks(
            known, hb, ts, gossip, aux, g, q, p, sp,
            s_ticks=s_ticks, **kern_kw)
        known, hb, ts, gossip, aux, sent, recv = out[:7]
        ev = out[7:] if with_events else (None, None)
        return (known, hb, ts, gossip), aux, t + s_ticks, sent, recv, ev

    def assemble(planes, aux, t, rng, sents, recvs, addeds, removeds):
        sent = jnp.concatenate(sents, 0) if sents \
            else jnp.zeros((0, n), jnp.int32)
        recv = jnp.concatenate(recvs, 0) if recvs \
            else jnp.zeros((0, n), jnp.int32)
        if with_events:
            added = jnp.concatenate(addeds, 0) > 0 if addeds \
                else jnp.zeros((0, n, n), bool)
            removed = jnp.concatenate(removeds, 0) > 0 if removeds \
                else jnp.zeros((0, n, n), bool)
        else:
            added = removed = jnp.zeros((sent.shape[0],), bool)
        ev = TickEvents(added=added, removed=removed,
                        sent=sent, recv=recv)
        return unpack(planes, aux, t, rng), ev

    def run_body(state: WorldState, sched: Schedule):
        planes0 = pack(state, sched)
        planes, aux = planes0[:4], planes0[4]
        t = state.tick
        sents, recvs, addeds, removeds = [], [], [], []
        if n_chunks:
            def step(carry, _):
                planes, aux, t = carry
                planes, aux, t, sent, recv, ev = launch(
                    planes, aux, t, state.rng, sched, s_full)
                out = (sent, recv) + (ev if with_events else ())
                return (planes, aux, t), out
            (planes, aux, t), outs = jax.lax.scan(
                step, (planes, aux, t), None, length=n_chunks)
            sents.append(outs[0].reshape(n_chunks * s_full, n))
            recvs.append(outs[1].reshape(n_chunks * s_full, n))
            if with_events:
                addeds.append(outs[2].reshape(n_chunks * s_full, n, n))
                removeds.append(outs[3].reshape(n_chunks * s_full, n, n))
        if rem:
            planes, aux, t, sent_r, recv_r, ev_r = launch(
                planes, aux, t, state.rng, sched, rem)
            sents.append(sent_r)
            recvs.append(recv_r)
            if with_events:
                addeds.append(ev_r[0])
                removeds.append(ev_r[1])
        return assemble(planes, aux, t, state.rng, sents, recvs,
                        addeds, removeds)

    if as_body:
        assert jax.default_backend() == "tpu"
        return run_body
    if jax.default_backend() == "tpu":
        return jax.jit(run_body, compiler_options={
            "xla_tpu_scoped_vmem_limit_kib": "114688"})

    def run_eager(state: WorldState, sched: Schedule):
        planes0 = pack(state, sched)
        planes, aux = planes0[:4], planes0[4]
        t = state.tick
        sents, recvs, addeds, removeds = [], [], [], []
        for s_ticks in [s_full] * n_chunks + ([rem] if rem else []):
            planes, aux, t, sent, recv, ev = launch(
                planes, aux, t, state.rng, sched, s_ticks)
            sents.append(sent)
            recvs.append(recv)
            if with_events:
                addeds.append(ev[0])
                removeds.append(ev[1])
        return assemble(planes, aux, t, state.rng, sents, recvs,
                        addeds, removeds)

    return run_eager
