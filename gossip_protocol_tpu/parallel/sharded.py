"""Sharded whole-run execution over a device mesh.

Scale-out replacement for the reference's single-process emulation
(SURVEY.md §2.3-2.4): the peer axis — and with it every row of the
(N, N) membership tables — is sharded over a 1-D ``jax.sharding.Mesh``
axis; (N,) vectors and the clock/key are replicated.  The whole
700-tick ``lax.scan`` runs inside one ``shard_map``, so per tick the
only cross-device traffic is one ``all_to_all`` (delivery transpose)
and the ``ppermute`` ring of the merge reduction — all ICI-resident
collectives, no host round-trips.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import SimConfig
from ..core.tick import TickEvents, make_tick
from ..state import Schedule, WorldState
from .comm import RingComm

PEER_AXIS = "peers"


def make_mesh(n_devices: Optional[int] = None, axis: str = PEER_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` available devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _state_specs(axis: str) -> WorldState:
    """PartitionSpecs per WorldState field: tables row-sharded, rest
    replicated."""
    mat = P(axis, None)
    rep = P()
    vec = P()
    return WorldState(tick=rep, in_group=vec, own_hb=vec,
                      known=mat, hb=mat, ts=mat,
                      gossip=mat, gossip_age=mat,
                      joinreq=vec, joinrep=vec, rng=rep)


def _sched_specs() -> Schedule:
    import dataclasses
    # every schedule field replicated — the (N,) vectors and world
    # fields (worlds.py) are small next to the row-sharded tables
    return Schedule(**{f.name: P()
                       for f in dataclasses.fields(Schedule)})


def peer_spec_trees(axis: str = PEER_AXIS) -> tuple:
    """The canonical peer-axis PartitionSpec trees ``(state, sched)``
    — the building block both the 2-D lanes×peers composition
    (parallel/fleet_mesh.py ``compose_lane_peer_specs``) and the
    analyzer's independent spec derivation
    (analysis/sharding_flow.py ``axes_tree_dims``) start from."""
    return _state_specs(axis), _sched_specs()


_SHARDED_CACHE: dict = {}


def make_sharded_run(cfg: SimConfig, mesh: Mesh, block_size: int = 128,
                     with_events: bool = True, axis: str = PEER_AXIS,
                     use_pallas: bool | None = None):
    """Build ``run(state, sched) -> (final_state, events)`` with the
    scan-over-ticks inside ``shard_map`` over ``mesh``.

    Events come back row-sharded: ``added``/``removed`` have shape
    [T, N//P, N] per device, i.e. logically [T, N, N] sharded on axis 1.
    """
    n_shards = mesh.devices.size
    comm = RingComm(axis, n_shards, use_pallas)
    key = (cfg.n, cfg.t_remove, cfg.total_ticks, block_size, with_events,
           axis, mesh, comm.use_pallas,
           cfg.rejoin_after is not None)
    if key in _SHARDED_CACHE:
        return _SHARDED_CACHE[key]
    tick = make_tick(cfg, block_size, comm=comm)

    state_specs = _state_specs(axis)
    ev_specs = TickEvents(added=P(None, axis, None),
                          removed=P(None, axis, None),
                          sent=P(None, axis), recv=P(None, axis))
    if not with_events:
        ev_specs = TickEvents(added=P(), removed=P(),
                              sent=P(None, axis), recv=P(None, axis))

    def body(state: WorldState, sched: Schedule):
        def step(carry, _):
            carry, ev = tick(carry, sched)
            if not with_events:
                ev = TickEvents(added=jnp.zeros((), bool),
                                removed=jnp.zeros((), bool),
                                sent=ev.sent, recv=ev.recv)
            return carry, ev
        return jax.lax.scan(step, state, None, length=cfg.total_ticks)

    from ..compat.jaxapi import shard_map
    shmapped = shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, _sched_specs()),
        out_specs=(state_specs, ev_specs),
    )
    run = jax.jit(shmapped)
    _SHARDED_CACHE[key] = run
    return run


def shard_state(state: WorldState, mesh: Mesh, axis: str = PEER_AXIS) -> WorldState:
    """Place a host/single-device WorldState onto the mesh with the
    canonical shardings (call once before the run loop)."""
    specs = _state_specs(axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs)
