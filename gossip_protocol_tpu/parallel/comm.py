"""Communication backends for the tick function.

The reference's "distributed backend" is EmulNet — a single shared
in-process buffer (EmulNet.h:35-72) scanned O(buffer) per node per tick
(EmulNet.cpp:151-174).  Here the equivalent component is a small
collective-communication abstraction over the peer-sharded state:

* :class:`LocalComm`  — single device; transposes are array transposes
  and reductions run in one pass.
* :class:`RingComm`   — the peer axis (and with it every row of the
  (N, N) membership tables) is sharded across a ``jax.sharding.Mesh``
  axis inside ``shard_map``.  Delivery consumption becomes one
  ``all_to_all`` (the matrix transpose from sender-major to
  receiver-major), and the gossip merge becomes a **ring reduction**:
  payload row-blocks rotate around the mesh axis with ``ppermute``
  while each device max-accumulates into its local receiver rows —
  the same blockwise pattern ring attention uses for long sequences,
  applied to the peer axis (SURVEY.md §2.3).  Collectives ride ICI
  inside a slice / DCN across slices; nothing here assumes either.

Both backends take ``use_pallas``: True routes the merge reduction
through the MXU level decomposition (ops/merge.py
gossip_reductions_mxu — one boolean matmul per distinct column value),
False through the blockwise VPU XLA op, None picks by backend (MXU on
TPU).  The two implementations share one output contract and are
differentially tested against each other (tests/test_pallas.py).

The tick body is written once against this interface; sharding is a
deployment choice, not a code path fork.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.merge import FILL, gossip_reductions


def _resolve_use_pallas(use_pallas):
    if use_pallas is None:
        return jax.default_backend() == "tpu"
    return bool(use_pallas)


def _merge_fn(use_pallas: bool):
    if use_pallas:
        from ..ops.merge import gossip_reductions_mxu
        return gossip_reductions_mxu
    return gossip_reductions


class LocalComm:
    """Single-device (or fully-replicated) execution."""

    n_shards = 1

    def __init__(self, use_pallas: bool | None = None):
        self.use_pallas = _resolve_use_pallas(use_pallas)
        self._merge = _merge_fn(self.use_pallas)

    def row_ids(self, n: int) -> jax.Array:
        """Global row indices of the locally-held row block."""
        return jnp.arange(n, dtype=jnp.int32)

    def transpose(self, x: jax.Array) -> jax.Array:
        """[rows=senders, N] -> [rows=receivers, N] reorientation."""
        return x.T

    def or_across(self, v: jax.Array) -> jax.Array:
        """Combine per-device partial ORs of a replicated-shape vector."""
        return v

    def gather_rows(self, v_local: jax.Array) -> jax.Array:
        """[local_rows] -> [N] (already global locally)."""
        return v_local

    def slice_rows(self, x: jax.Array) -> jax.Array:
        """Slice a replicated [N, ...] array down to the local rows."""
        return x

    def merge_reduce(self, recv_from, known, hb, ts, now, *,
                     t_remove: int, block_size: int):
        return self._merge(recv_from, known, hb, ts, now,
                           t_remove=t_remove, block_size=block_size)


class RingComm:
    """Peer-axis-sharded execution inside ``shard_map``.

    Must be used with every (N, N) table sharded as
    ``P(axis_name, None)`` and every (N,) vector replicated.
    ``n`` must be divisible by the mesh axis size.
    """

    def __init__(self, axis_name: str, n_shards: int,
                 use_pallas: bool | None = None):
        self.axis = axis_name
        self.n_shards = n_shards
        self.use_pallas = _resolve_use_pallas(use_pallas)
        self._merge = _merge_fn(self.use_pallas)

    def row_ids(self, n: int) -> jax.Array:
        nl = n // self.n_shards
        return jnp.arange(nl, dtype=jnp.int32) + lax.axis_index(self.axis) * nl

    def transpose(self, x: jax.Array) -> jax.Array:
        """Distributed transpose: sender-row-sharded [Nl, N] ->
        receiver-row-sharded [Nl, N] via one all_to_all."""
        nl, n = x.shape
        p = self.n_shards
        # [Nl_s, P, Nl_r] -> per-destination blocks on the leading axis
        z = x.reshape(nl, p, nl).swapaxes(0, 1)          # [P, Nl_s, Nl_r]
        w = lax.all_to_all(z, self.axis, 0, 0)           # [P, Nl_s, Nl_r] from each origin
        # received block o is x_o[:, mine].  Transpose to receiver-major.
        return w.transpose(2, 0, 1).reshape(nl, n)

    def or_across(self, v: jax.Array) -> jax.Array:
        return lax.psum(v.astype(jnp.int32), self.axis) > 0

    def gather_rows(self, v_local: jax.Array) -> jax.Array:
        return lax.all_gather(v_local, self.axis, tiled=True)

    def slice_rows(self, x: jax.Array) -> jax.Array:
        nl = x.shape[0] // self.n_shards
        start = lax.axis_index(self.axis) * nl
        return lax.dynamic_slice_in_dim(x, start, nl, axis=0)

    def merge_reduce(self, recv_from, known, hb, ts, now, *,
                     t_remove: int, block_size: int):
        """Ring max-accumulation over rotating payload blocks.

        recv_from: [Nl_r, N] local receiver rows (post-transpose).
        known/hb/ts: [Nl, N] local payload rows (this device's peers).
        """
        nl, n = known.shape
        p = self.n_shards
        me = lax.axis_index(self.axis)
        perm = [(i, (i + 1) % p) for i in range(p)]
        merge = self._merge

        def step(k, carry):
            m_all, m_fr, t_fr, anyf, kb, hbb, tsb = carry
            # the rotating block currently holds rows of origin device o
            o = (me - k) % p
            cols = lax.dynamic_slice(recv_from, (0, o * nl), (nl, nl))
            r = merge(cols, kb, hbb, tsb, now,
                      t_remove=t_remove, block_size=block_size)
            m_all = jnp.maximum(m_all, r[0])
            m_fr = jnp.maximum(m_fr, r[1])
            t_fr = jnp.maximum(t_fr, r[2])
            anyf = anyf | r[3]
            kb = lax.ppermute(kb, self.axis, perm)
            hbb = lax.ppermute(hbb, self.axis, perm)
            tsb = lax.ppermute(tsb, self.axis, perm)
            return (m_all, m_fr, t_fr, anyf, kb, hbb, tsb)

        # input-derived initializers: keep the fori_loop carry's
        # varying-axis type consistent under shard_map (see ops/merge.py)
        zero = recv_from[:, :1].astype(jnp.int32) * (hb[:1, :] * 0)
        init = (zero + FILL, zero + FILL, zero + FILL, zero.astype(bool),
                known, hb, ts)
        m_all, m_fr, t_fr, anyf, *_ = lax.fori_loop(0, p, step, init)
        return m_all, m_fr, t_fr, anyf
