"""Mesh-parallel fleet: the lane axis sharded over a device mesh.

parallel/sharded.py scales ONE simulation out by sharding the peer
axis — and pays an ``all_to_all`` plus a ``ppermute`` ring every tick
for it (docs/PERF.md §4).  The fleet's LANE axis (core/fleet.py) is
the opposite kind of parallel: B independent simulations share only
the unbatched clock and (within a bucket) the drop plan, so sharding
lanes over a 1-D mesh costs ZERO collectives per tick — it is plain
data parallelism, the same shape a training stack gives its batch
axis under GSPMD/pjit, and the capacity axis an Orca-style
continuous-batching server schedules against.

Why it pays even on one host: the kernels are op-*issue*-bound
(PERF §3, §8) — the machine spends its time issuing per-tick ops, not
computing them — and a vmapped fleet still issues every op from ONE
program stream, which is exactly why the single-device fleet curve
flattens near B≈8–16.  ``shard_map`` over D devices gives D
concurrent program streams (XLA:CPU executes each shard's partition
on its own dispatch thread; on TPU each chip runs its own program),
attacking the issue bottleneck the vmap lever cannot reach.

Shape of the thing (``MeshFleetSimulation`` — a drop-in
:class:`~..core.fleet.FleetSimulation` with a mesh):

* **Lane-sharded stacks.**  States and schedules are stacked exactly
  as in core/fleet.py, then placed with ``NamedSharding``: every
  lane-batched leaf is split over ``LANE_AXIS``; each shard runs the
  same vmapped scan over its B/D local lanes inside one ``shard_map``
  (donated carry, one jitted program).
* **The clock and the drop plane are REPLICATED.**  The replicated
  set is *definitionally* the unbatched set: PartitionSpecs are
  derived from the fleet's vmap axes trees (``WORLD_AXES``,
  ``SCHED_AXES_SHARED_DROP``), so the PR-3 shared-drop rule survives
  sharding by construction.  This is load-bearing the same way it was
  under vmap: a per-shard (or per-lane) ``drop_active`` would
  re-degrade the drop ``lax.cond`` to a both-branches select —
  pinned by tests/test_fleet_mesh.py's jaxpr regression.
* **Bit-identical lanes.**  A lane's trajectory is integer/bool/PRNG
  arithmetic with no cross-lane reduction, so mesh lanes replay
  single-device fleet lanes — and solo runs — bit-for-bit
  (tests/test_fleet_mesh.py, D ∈ {2, 4, 8} virtual CPU devices).
* **Batch must divide the mesh.**  ``B % D == 0`` is enforced with an
  actionable error; the serving layer pads dispatches to a
  shard-divisible width (service/scheduler.py ``pad_policy`` × mesh
  factor).

Compiled programs live in the process-wide ``_FLEET_FN_CACHE`` with
the mesh descriptor in the key (core/fleet.py ``_mesh_entry``): a
device-count change can never be served a stale program.

This lane mesh composes with §4's peer sharding as a 2-D mesh
(lanes × peers): the per-tick collectives stay *within* each lane's
peer-axis submesh, and the lane axis still moves zero bytes.  Since
PR 19 the composition is the PRODUCTION path, not a prototype:
:class:`MeshFleetSimulation` (and therefore ``FleetService(mesh=)``)
accepts a 2-D ``Mesh((lanes, peers))`` directly — dense programs
whose world width divides the peer axis run with the
:class:`~.comm.RingComm` exchange inside the shard_mapped tick
(``_peer_comm``); worlds that do not divide (and the overlay model,
whose partial-view tick has no peer decomposition) serve with the
peer axis REPLICATED, which is bit-identical by construction because
every peer shard runs the same deterministic integer program.  The
elastic ladder is axis-aware (:func:`shrink_mesh` halves the PEER
axis of a 2-D mesh before it ever touches a lane; :func:`grow_mesh`
steps it back up toward the captured full shape), and the standalone
:func:`make_lane_peer_bench_fn` remains the analyzer's
contract-carrying registration (``mesh2d-lanes-peers``,
analysis/sharding_flow.py) alongside the production serving program
(``mesh2d-serving``).  Hardware validation remains PERF §10 work.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compat.jaxapi import shard_map
from ..config import SimConfig
from ..core.fleet import (EVENT_AXES, SCHED_AXES_BATCHED,
                          SCHED_AXES_CANON, SCHED_AXES_SHARED_DROP,
                          WORLD_AXES, CanonicalFleetSimulation,
                          FleetSimulation)
from ..core.tick import TickEvents, make_tick

LANE_AXIS = "lanes"


def make_lane_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D lane mesh over the first ``n_devices`` available devices."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"asked for a {n_devices}-device lane mesh but only "
                f"{len(devs)} devices are available "
                f"(backend={jax.default_backend()}; CPU runs force "
                "virtual devices via "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "before jax is first imported)")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (LANE_AXIS,))


def mesh_descriptor(mesh: Mesh) -> tuple:
    """Hashable identity of a serving mesh for program-cache keys.

    Carries the device SHAPE as well as the flat ids: a 2×4 and a 4×2
    mesh over the same eight devices compile different programs (the
    peer decomposition differs), so their descriptors must differ too.
    """
    return (mesh.axis_names, tuple(d.id for d in mesh.devices.flat),
            tuple(mesh.devices.shape))


def mesh_axis_sizes(mesh: Optional[Mesh]) -> tuple:
    """``(n_lanes, n_peers, peer_axis)`` of a serving mesh, validating
    the accepted shapes: ``None`` (no mesh — one lane slot, no peer
    axis), a 1-D lane mesh, or the 2-D ``Mesh((lanes, peers))``
    composition.  Anything else — a transposed axis order, a 3-D
    mesh, foreign axis names — is rejected here, once, so the service
    and the fleet agree on what a mesh means."""
    if mesh is None:
        return 1, 1, None
    names, shape = mesh.axis_names, tuple(mesh.devices.shape)
    if mesh.devices.ndim == 1 and len(names) == 1:
        return int(shape[0]), 1, None
    from .sharded import PEER_AXIS
    if mesh.devices.ndim == 2 and names == (LANE_AXIS, PEER_AXIS):
        return int(shape[0]), int(shape[1]), PEER_AXIS
    raise ValueError(
        f"serving meshes are 1-D ({LANE_AXIS!r},) or 2-D "
        f"({LANE_AXIS!r}, {PEER_AXIS!r}); got axes {names} "
        f"shape {shape}")


def shrink_mesh(mesh: Optional[Mesh]) -> Optional[Mesh]:
    """One rung down the serving degradation ladder, axis-aware.

    A 2-D lanes×peers mesh loses a PEER shard first: the peer axis is
    HALVED (power-of-two peer counts keep every remaining width
    peer-shard-divisible) over the flat device prefix, lanes
    untouched; at one peer the mesh collapses to the 1-D lane mesh.
    A 1-D mesh drops its LAST device (``None`` once fewer than two
    remain — the single-device fleet needs no mesh at all).  Devices
    are always kept as a PREFIX of the current flat order, so the
    ladder's descriptors are a pure function of the rung — a
    shrink→grow cycle re-keys back to descriptors that served before
    (service/cache.py ``rebind_mesh``).

    This is the rebuild path the service takes on a (simulated or
    real) device loss: the shrunken mesh has a fresh
    :func:`mesh_descriptor`, so every program cache that keys on the
    mesh (``_FLEET_FN_CACHE``, the service ``ProgramCache``) misses by
    construction and the bucket recompiles for the smaller device set
    — a stale wide program can never be dispatched onto the survivors
    (service/scheduler.py ``_degrade_mesh``).
    """
    if mesh is None:
        return None
    if mesh.devices.ndim == 2:
        lanes, peers = mesh.devices.shape
        new_peers = peers // 2
        devs = list(mesh.devices.flat)[:lanes * max(1, new_peers)]
        if new_peers <= 1:
            if len(devs) < 2:
                return None
            return Mesh(np.array(devs), (LANE_AXIS,))
        return Mesh(np.array(devs).reshape(lanes, new_peers),
                    mesh.axis_names)
    devs = list(mesh.devices.flat)[:-1]
    if len(devs) < 2:
        return None
    return Mesh(np.array(devs), mesh.axis_names)


def grow_mesh(mesh: Optional[Mesh], devices,
              full_shape: Optional[tuple] = None,
              full_axes: Optional[tuple] = None) -> Optional[Mesh]:
    """One rung UP the elasticity ladder — the inverse of
    :func:`shrink_mesh`: the same mesh re-extended from ``devices``,
    the full-strength device tuple the service captured at
    construction.

    :func:`shrink_mesh` always keeps a PREFIX of the flat device
    order, so growing re-extends the prefix.  On the 1-D ladder (no
    ``full_shape``, or a 1-D one) that is one device at a time
    (``None`` — the single-device rung — grows straight to a fresh
    2-device mesh, mirroring shrink's below-2 collapse).  With a 2-D
    ``full_shape`` the lane axis is restored first, then the peer
    axis DOUBLES back toward the full shape — the exact inverse of
    the peer-halving shrink, so each grown descriptor equals the
    descriptor the same rung had on the way down and the final grow
    restores the original 2-D descriptor exactly.  The service
    ProgramCache then finds the retained handles and programs again
    (service/cache.py ``rebind_mesh`` re-keys rather than evicts).
    Already at full strength (or ``devices`` is None — the service
    never had a mesh): returned unchanged.
    """
    if devices is None:
        return mesh
    devs = list(devices)
    if full_shape is not None and len(full_shape) == 2:
        full_lanes, full_peers = (int(full_shape[0]), int(full_shape[1]))
        if mesh is None:
            cur_lanes, cur_peers = 0, 1
        elif mesh.devices.ndim == 1:
            cur_lanes, cur_peers = int(mesh.devices.size), 1
        else:
            cur_lanes, cur_peers = mesh.devices.shape
        if cur_lanes < full_lanes:
            nk = min(max(2, cur_lanes + 1), full_lanes, len(devs))
            if nk <= cur_lanes:
                return mesh
            return Mesh(np.array(devs[:nk]), (LANE_AXIS,))
        new_peers = min(max(2, cur_peers * 2), full_peers)
        if new_peers <= cur_peers or full_lanes * new_peers > len(devs):
            return mesh
        if full_axes is None:
            from .sharded import PEER_AXIS
            full_axes = (LANE_AXIS, PEER_AXIS)
        return Mesh(np.array(devs[:full_lanes * new_peers])
                    .reshape(full_lanes, new_peers), tuple(full_axes))
    k = int(mesh.devices.size) if mesh is not None else 1
    nk = max(2, k + 1)
    if k >= len(devs) or nk > len(devs):
        return mesh
    names = mesh.axis_names if mesh is not None else (LANE_AXIS,)
    return Mesh(np.array(devs[:nk]), names)


def _axes_to_specs(axes):
    """vmap axes tree -> PartitionSpec tree: batched leaves are
    lane-sharded, unbatched leaves (the clock, the shared drop plane)
    are replicated.  Deriving specs from the axes tree keeps the
    replicated set identical to the unbatched set by construction."""
    cls = type(axes)
    return cls(**{f.name: (P() if getattr(axes, f.name) is None
                           else P(LANE_AXIS))
                  for f in dataclasses.fields(cls)})


def _all_lane_specs(cls):
    """Every field of ``cls`` lane-sharded on its leading axis."""
    return cls(**{f.name: P(LANE_AXIS) for f in dataclasses.fields(cls)})


# ---- the 2-D lanes x peers composition (PERF §10 prototype) ----------
#: static collective equations per traced dense tick on the peer axis:
#: RingComm.merge_reduce's fori_loop body carries 3 ppermutes (known /
#: heartbeat / timestamp rings), the XOR exchange is 1 all_to_all, and
#: the membership vote is 1 psum.  The sharding-flow auditor holds the
#: registered 2-D program to this budget — a bust means a collective
#: joined the per-tick hot loop (analysis/sharding_flow.py).
LANE_PEER_TICK_COLLECTIVE_BUDGET = 5


def make_lane_peer_mesh(n_lanes: int, n_peers: int) -> Mesh:
    """2-D ``Mesh((lanes, peers))``: the lane mesh composed with the
    peer-sharding axis of parallel/sharded.py."""
    from .sharded import PEER_AXIS
    devs = jax.devices()
    need = n_lanes * n_peers
    if need > len(devs):
        raise ValueError(
            f"asked for a {n_lanes}x{n_peers} lanes x peers mesh but "
            f"only {len(devs)} devices are available "
            f"(backend={jax.default_backend()}; CPU runs force virtual "
            "devices via "
            "XLA_FLAGS=--xla_force_host_platform_device_count=N "
            "before jax is first imported)")
    return Mesh(np.array(devs[:need]).reshape(n_lanes, n_peers),
                (LANE_AXIS, PEER_AXIS))


def compose_lane_peer_specs(lane_axes, peer_specs):
    """Compose a fleet vmap-axes tree with a peer-axis PartitionSpec
    tree into the 2-D spec tree: a lane-batched leaf gains a leading
    ``LANE_AXIS`` dim ahead of its peer spec; an unbatched leaf (the
    clock, the shared drop plane) keeps only its peer spec — which is
    ``P()`` for the replicated plane, preserving the PR-3 shared-drop
    rule in both mesh dimensions by construction.  The analyzer
    re-derives this composition independently and fails
    ``spec-derivation-consistent`` with the offending leaf path if the
    two ever drift (analysis/sharding_flow.py)."""
    cls = type(lane_axes)
    out = {}
    for f in dataclasses.fields(cls):
        la = getattr(lane_axes, f.name)
        ps = getattr(peer_specs, f.name)
        out[f.name] = ps if la is None else P(LANE_AXIS, *ps)
    return cls(**out)


def make_lane_peer_bench_fn(cfg: SimConfig, mesh: Mesh,
                            block_size: int = 128):
    """The 2-D prototype program: the fleet's vmapped dense tick with
    the RingComm peer exchange inside, scanned and shard_mapped over
    ``Mesh((lanes, peers))`` with the carry donated.

    Each lane's peer collectives stay within its own peer-axis submesh
    and the lane axis moves zero bytes — per-lane results are
    bit-identical to the 1-D lane fleet (tests/test_fleet_mesh.py runs
    the parity on 8 virtual CPU devices).  Returns the raw jitted
    program ``(states, scheds) -> (states, (sent, recv))``.  Since
    PR 19 the same composition serves through
    :class:`MeshFleetSimulation` (``_peer_comm``); this standalone
    builder remains the analyzer's minimal contract-carrying
    registration (``mesh2d-lanes-peers``) next to the production
    serving program (``mesh2d-serving``).
    """
    from .comm import RingComm
    from .sharded import peer_spec_trees
    ax = dict(zip(mesh.axis_names, mesh.devices.shape))
    peer_axis = [a for a in mesh.axis_names if a != LANE_AXIS]
    if LANE_AXIS not in ax or len(peer_axis) != 1:
        raise ValueError(
            f"make_lane_peer_bench_fn takes a 2-D ({LANE_AXIS!r}, "
            f"peer) mesh, got axes {mesh.axis_names}")
    peer_axis = peer_axis[0]
    n_peers = ax[peer_axis]
    if cfg.n % n_peers:
        raise ValueError(
            f"world of n={cfg.n} nodes does not divide over the "
            f"{n_peers}-device {peer_axis!r} axis")
    tick = make_tick(cfg, block_size, use_pallas=False,
                     with_events=False,
                     comm=RingComm(peer_axis, n_peers, use_pallas=False))
    vtick = jax.vmap(tick, in_axes=(WORLD_AXES, SCHED_AXES_SHARED_DROP),
                     out_axes=(WORLD_AXES, EVENT_AXES))
    total = cfg.total_ticks

    def body(states, scheds):
        def step(carry, _):
            carry, ev = vtick(carry, scheds)
            return carry, (ev.sent, ev.recv)
        return jax.lax.scan(step, states, None, length=total)

    peer_state, peer_sched = peer_spec_trees(peer_axis)
    state_specs = compose_lane_peer_specs(WORLD_AXES, peer_state)
    sched_specs = compose_lane_peer_specs(SCHED_AXES_SHARED_DROP,
                                          peer_sched)
    # scan stacks ticks leading: (T, B, width) counters
    cnt = P(None, LANE_AXIS, peer_axis)
    return jax.jit(shard_map(body, mesh=mesh,
                             in_specs=(state_specs, sched_specs),
                             out_specs=(state_specs, (cnt, cnt))),
                   donate_argnums=(0,))


def _shardings(specs, mesh: Mesh):
    """PartitionSpec tree -> NamedSharding tree (build once, reuse:
    NamedSharding construction is pure host overhead on the pack path)."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)


def _place(tree, specs, mesh: Mesh):
    """Put a stacked pytree onto the mesh with the given specs (one
    BATCHED device_put for the whole tree — per-leaf python calls were
    a measurable slice of the serving pack cost)."""
    return jax.device_put(tree, _shardings(specs, mesh))


class MeshFleetSimulation(FleetSimulation):
    """:class:`~..core.fleet.FleetSimulation` with the lane axis
    sharded over a device mesh — 1-D (lanes) or 2-D (lanes × peers).

    Same API and same per-lane results (bit-identical) as the
    single-device fleet; the batch must be a multiple of the LANE
    axis size.  ``run``/``run_bench`` accept the same ``seeds=``/
    ``configs=``/``n_real=`` arguments — the serving layer drives
    this class through the unchanged scheduler with shard-divisible
    padding (service/scheduler.py ``mesh=``).

    On a 2-D mesh, dense programs whose width divides the peer axis
    run the :class:`~.comm.RingComm` exchange inside the
    shard_mapped tick (each lane's collectives confined to its own
    peer submesh — the composition :func:`make_lane_peer_bench_fn`
    prototyped); everything else (non-divisible widths, the overlay
    model) serves with the peer axis replicated — correct because
    every peer shard runs the same deterministic integer program, at
    the cost of redundant peer-axis compute for those buckets.
    """

    def __init__(self, cfg: SimConfig, mesh: Optional[Mesh] = None,
                 block_size: int = 128,
                 chunk_ticks: Optional[int] = None):
        super().__init__(cfg, block_size=block_size,
                         chunk_ticks=chunk_ticks)
        self.mesh = mesh if mesh is not None else make_lane_mesh()
        self._n_lanes, self._n_peers, self._peer_axis = \
            mesh_axis_sizes(self.mesh)

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def n_lanes(self) -> int:
        """Lane-axis width — the batch-divisibility unit (== device
        count on a 1-D mesh)."""
        return self._n_lanes

    @property
    def n_peers(self) -> int:
        """Peer-axis width (1 on a 1-D mesh)."""
        return self._n_peers

    # ---- program-cache identity -------------------------------------
    def _mesh_entry(self):
        return mesh_descriptor(self.mesh)

    # ---- staging placement ------------------------------------------
    def _staging_out_shardings(self, axes_tree):
        """Staged init states are born lane-sharded (the init program
        compiles with these out_shardings), so the run wrapper's
        device_put is a no-op instead of a 9-leaf resharding copy."""
        return _shardings(_axes_to_specs(axes_tree), self.mesh)

    # ---- lane validation --------------------------------------------
    def _lane_cfgs(self, seeds, configs):
        cfgs = super()._lane_cfgs(seeds, configs)
        d = self.n_lanes
        if len(cfgs) % d:
            raise ValueError(
                f"fleet of {len(cfgs)} lanes does not divide over the "
                f"{d}-wide {LANE_AXIS!r} axis; pad to a multiple of "
                f"{d} (the serving layer's pad policies do this — "
                "service/scheduler.py)")
        return cfgs

    # ---- the peer axis -----------------------------------------------
    def _peer_comm(self, n: int):
        """The peer-axis exchange for an ``n``-peer world, or ``None``
        when the program serves peer-replicated: no peer axis on the
        mesh, or a width that does not divide it (the pad ladder under
        ``canonicalize`` snaps widths to peer-divisible rungs; exact
        buckets keep the member width and fall back to replication)."""
        if self._peer_axis is None or n % self._n_peers:
            return None
        from .comm import RingComm
        return RingComm(self._peer_axis, self._n_peers, use_pallas=False)

    def _peer_specs(self, axes):
        """``(state_specs, sched_specs)`` for one dense program with
        the peer axis composed in (:func:`compose_lane_peer_specs`
        over the peer-axis spec trees of parallel/sharded.py)."""
        from .sharded import peer_spec_trees
        peer_state, peer_sched = peer_spec_trees(self._peer_axis)
        return (compose_lane_peer_specs(WORLD_AXES, peer_state),
                compose_lane_peer_specs(axes, peer_sched))

    # ---- shared build plumbing --------------------------------------
    def _shard_run(self, body, state_specs, sched_specs, out_specs):
        """jit(shard_map(body)) with the carry donated, wrapped so the
        stacked host inputs are placed with the canonical shardings on
        every call.  The raw jitted program is exposed as ``.jitted``
        for the drop-plane jaxpr regression (tests/test_fleet_mesh.py).
        """
        mesh = self.mesh
        shmapped = shard_map(body, mesh=mesh,
                             in_specs=(state_specs, sched_specs),
                             out_specs=out_specs)
        jitted = jax.jit(shmapped, donate_argnums=(0,))
        state_sh = _shardings(state_specs, mesh)
        sched_sh = _shardings(sched_specs, mesh)

        def run(states, scheds):
            # one batched device_put per tree; states usually arrive
            # pre-placed (the staging init compiles with out_shardings
            # — _staging_out_shardings), making this a cheap no-op
            placed = (jax.device_put(states, state_sh),
                      jax.device_put(scheds, sched_sh))
            out = jitted(*placed)
            # the placed state tree was DONATED into the (async)
            # program: letting it die while the program runs blocks
            # the host until completion (core/fleet.py PendingFleet).
            # Park it for the launch path to hold until resolve; a
            # stale parked ref from an already-completed call is
            # overwritten here, which is free.
            run.held = placed
            return out

        run.jitted = jitted
        return run

    # ---- dense bench ------------------------------------------------
    def _dense_bench_fn(self, batch: int, width: int, shared_drop: bool):
        def build():
            cfg_w = self.cfg.replace(max_nnb=width)
            comm = self._peer_comm(cfg_w.n)
            tick = make_tick(cfg_w, self.block_size, use_pallas=False,
                             with_events=False, comm=comm)
            axes = SCHED_AXES_SHARED_DROP if shared_drop \
                else SCHED_AXES_BATCHED
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                             out_axes=(WORLD_AXES, EVENT_AXES))
            total = self.cfg.total_ticks

            def body(states, scheds):
                def step(carry, _):
                    carry, ev = vtick(carry, scheds)
                    return carry, (ev.sent, ev.recv)
                return jax.lax.scan(step, states, None, length=total)

            if comm is None:
                state_specs = _axes_to_specs(WORLD_AXES)
                sched_specs = _axes_to_specs(axes)
                # scan stacks ticks leading: (T, B, width) counters
                cnt = P(None, LANE_AXIS)
            else:
                state_specs, sched_specs = self._peer_specs(axes)
                cnt = P(None, LANE_AXIS, self._peer_axis)
            return self._shard_run(body, state_specs, sched_specs,
                                   (state_specs, (cnt, cnt)))

        return self._fleet_program(self._cache_key("bench", batch, width,
                                         shared_drop), build)

    # ---- dense trace -------------------------------------------------
    def _dense_trace_fn(self, batch: int, length: int, shared_drop: bool):
        def build():
            comm = self._peer_comm(self.cfg.n)
            tick = make_tick(self.cfg, self.block_size, use_pallas=False,
                             with_events=True, comm=comm)
            axes = SCHED_AXES_SHARED_DROP if shared_drop \
                else SCHED_AXES_BATCHED
            vtick = jax.vmap(tick, in_axes=(WORLD_AXES, axes),
                             out_axes=(WORLD_AXES, EVENT_AXES))

            def body(states, scheds):
                def step(carry, _):
                    return vtick(carry, scheds)
                return jax.lax.scan(step, states, None, length=length)

            if comm is None:
                state_specs = _axes_to_specs(WORLD_AXES)
                sched_specs = _axes_to_specs(axes)
                ev = P(None, LANE_AXIS)    # (T, B, ...) event stacks
                ev_specs = TickEvents(added=ev, removed=ev,
                                      sent=ev, recv=ev)
            else:
                state_specs, sched_specs = self._peer_specs(axes)
                # events are row-sharded over the peer axis exactly as
                # in parallel/sharded.py make_sharded_run: the (n, n)
                # matrices on their row dim, the (n,) counters whole
                em = P(None, LANE_AXIS, self._peer_axis, None)
                ev = P(None, LANE_AXIS, self._peer_axis)
                ev_specs = TickEvents(added=em, removed=em,
                                      sent=ev, recv=ev)
            return self._shard_run(body, state_specs, sched_specs,
                                   (state_specs, ev_specs))

        return self._fleet_program(self._cache_key("trace", batch, length,
                                         shared_drop), build)

    # ---- overlay (metrics mode) --------------------------------------
    def _overlay_fleet_fn(self, batch: int,
                          length: Optional[int] = None,
                          start_tick: int = 0):
        # start_tick is accepted for signature parity with the base
        # class but unused: the mesh path always runs the XLA vmap
        # tick, which reads the clock from the carried state (the grid
        # kernel does not shard_map — see the build comment below)
        from ..models.overlay import (OVERLAY_FLEET_STATE_AXES,
                                      OverlayMetrics, OverlaySchedule,
                                      make_overlay_tick)
        length = self.cfg.total_ticks if length is None else length

        def build():
            # the pure-XLA tick, coverage elided — identical routing to
            # make_overlay_fleet_run's vmap path; the TPU grid kernel's
            # leading batch grid dimension does not shard_map (Mosaic
            # owns its own grid), so a TPU lane mesh would run the
            # SAME per-shard grid fleet — documented in PERF §10, not
            # compiled here (no hardware to validate on)
            tick = make_overlay_tick(self.cfg, use_pallas=False,
                                     with_coverage=False)
            state_axes = OVERLAY_FLEET_STATE_AXES
            vtick = jax.vmap(tick, in_axes=(state_axes, 0),
                             out_axes=(state_axes, 0))

            def body(states, scheds):
                def step(carry, _):
                    return vtick(carry, scheds)
                finals, mets = jax.lax.scan(step, states, None,
                                            length=length)
                # (T, B) per-tick counters -> the (B, T) fleet contract
                return finals, jax.tree.map(lambda m: m.T, mets)

            state_specs = _axes_to_specs(state_axes)
            return self._shard_run(body, state_specs,
                                   _all_lane_specs(OverlaySchedule),
                                   (state_specs,
                                    _all_lane_specs(OverlayMetrics)))

        return self._fleet_program(self._cache_key("overlay", batch, length), build)


class CanonicalMeshFleetSimulation(MeshFleetSimulation,
                                   CanonicalFleetSimulation):
    """A canonical equivalence class (core/fleet.py
    :class:`~..core.fleet.CanonicalFleetSimulation`) served from a
    device mesh: the rung-width canonical program shard_mapped over
    the lane axis.

    ``rung_multiple`` pins the pad-ladder to peer-shard-divisible
    rungs (service/canonical.py ``ladder_rung(multiple=)``): on a 2-D
    mesh the service passes its FULL-STRENGTH peer count, fixed for
    the service's lifetime, so canonical bucket keys — and therefore
    the class membership — never move when the elastic ladder halves
    the peer axis (a rung divisible by the full power-of-two peer
    count stays divisible by every halved one).  The canonical
    program itself runs peer-REPLICATED: its rung re-shapes the world
    (filler peer rows), and the drop stream's corner embedding is
    defined on the whole table — replication keeps each peer shard
    running the identical deterministic program, preserving the
    bit-parity contract, while the snapped rung keeps the 2-D
    descriptors consistent for a future peer-sharded rung program.

    Like the base canonical class, monolithic trace dispatches only —
    leg entrypoints raise the typed
    :class:`~..service.canonical.CanonicalLegUnsupported` at lookup,
    and the service refuses the combination at construction.
    """

    def __init__(self, cfg: SimConfig, mesh: Optional[Mesh] = None,
                 block_size: int = 128,
                 chunk_ticks: Optional[int] = None,
                 rung_multiple: int = 1):
        m = int(rung_multiple)
        if m < 1 or m & (m - 1):
            raise ValueError(
                f"rung_multiple must be a power of two (the pad "
                f"ladder doubles), got {rung_multiple}")
        # read by CanonicalFleetSimulation.__init__ (reached through
        # MeshFleetSimulation's super() chain) for the rung snap
        self._rung_multiple = m
        MeshFleetSimulation.__init__(self, cfg, mesh=mesh,
                                     block_size=block_size,
                                     chunk_ticks=chunk_ticks)

    def _canon_trace_fn(self, batch: int, length: int):
        def build():
            body = self._canon_run_builder(length)
            state_specs = _axes_to_specs(WORLD_AXES)
            ev = P(None, LANE_AXIS)        # (T, B, ...) event stacks
            ev_specs = TickEvents(added=ev, removed=ev, sent=ev, recv=ev)
            return self._shard_run(body, state_specs,
                                   _axes_to_specs(SCHED_AXES_CANON),
                                   (state_specs, ev_specs))
        return self._fleet_program(
            self._cache_key("canon-trace", batch, length), build)
