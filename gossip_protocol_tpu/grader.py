"""Acceptance grader: the Grader.sh checks, reimplemented over dbg.log.

The reference's grading harness (Grader.sh:40-189) greps dbg.log for
"joined"/"removed"/"Node failed at time" lines and scores three
scenarios (max attainable 90/100 — the msgdrop accuracy block is
commented out, Grader.sh:181-189).  This module reproduces those checks
line-for-line in Python — including grep's *substring* matching of
address strings — so it can grade this framework's output and the
reference binary's output identically.

Run all three scenarios and grade them:

    python -m gossip_protocol_tpu.grader [--testcases DIR]
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field


def _lines(dbg_path: str, needle: str) -> list[str]:
    with open(dbg_path) as f:
        return [ln for ln in f.read().split("\n") if needle in ln]


def _uniq(lines: list[str]) -> list[str]:
    return sorted(set(lines))


def _observer(line: str) -> str:
    """Field 2 of a log line (cut -d' ' -f2): the observer address."""
    return line.split(" ")[1] if line.startswith(" ") else line.split(" ")[0]


def _subject(line: str) -> str:
    """The 'Node <addr>' subject of a joined/removed line."""
    m = re.search(r"Node (\S+) (?:joined|removed)", line)
    return m.group(1) if m else ""


@dataclass
class ScenarioGrade:
    name: str
    join_points: int = 0
    join_max: int = 10
    completeness_points: int = 0
    completeness_max: int = 10
    accuracy_points: int = 0
    accuracy_max: int = 10
    detail: dict = field(default_factory=dict)

    @property
    def points(self) -> int:
        return self.join_points + self.completeness_points + self.accuracy_points

    @property
    def max_points(self) -> int:
        return self.join_max + self.completeness_max + self.accuracy_max


def check_join(dbg_path: str, n: int = 10) -> bool:
    """Join completeness (Grader.sh:40-60): either N*N unique
    (observer, subject-phrase) pairs, or every one of N observers saw
    N-1 distinct others."""
    joined = _uniq(_lines(dbg_path, "joined"))
    pairs = {(_observer(ln), _subject(ln)) for ln in joined}
    if len(pairs) == n * n:
        return True
    observers = {_observer(ln) for ln in joined}
    ok = 0
    for obs in observers:
        subs = {_subject(ln) for ln in joined
                if _observer(ln) == obs and obs not in _subject(ln)}
        if len(subs) == n - 1:
            ok += 1
    return ok == n


def failed_addrs(dbg_path: str) -> list[str]:
    """Failed-node addresses (Grader.sh:61: awk '{print $1}' on the
    'Node failed at time' lines — $1 is the observer address because the
    line starts with a space)."""
    return _uniq([_observer(ln) for ln in _lines(dbg_path, "Node failed at time")])


def grade_single(dbg_path: str, n: int = 10,
                 join_pts: int = 10, comp_pts: int = 10,
                 acc_pts: int | None = 10) -> ScenarioGrade:
    """Single-failure scoring (Grader.sh:40-76; msgdrop variant uses
    15/15 and skips accuracy, Grader.sh:152-189)."""
    g = ScenarioGrade("single", join_max=join_pts, completeness_max=comp_pts,
                      accuracy_max=acc_pts or 0)
    if check_join(dbg_path, n):
        g.join_points = join_pts
    failed = failed_addrs(dbg_path)
    removed = _uniq(_lines(dbg_path, "removed"))
    failcount = sum(1 for ln in removed if any(a in ln for a in failed))
    g.detail["failcount"] = failcount
    if failcount >= n - 1:
        g.completeness_points = comp_pts
    if acc_pts:
        wrong = sum(1 for ln in removed if not any(a in ln for a in failed))
        g.detail["false_removals"] = wrong
        if wrong == 0 and failcount > 0:
            g.accuracy_points = acc_pts
    return g


def grade_multi(dbg_path: str, n: int = 10) -> ScenarioGrade:
    """Multi-failure scoring (Grader.sh:89-139): per failed node,
    completeness needs >=5 observers (2 pts each, first 6 nodes checked);
    accuracy needs exactly 20 unique removal lines not mentioning it."""
    g = ScenarioGrade("multi")
    if check_join(dbg_path, n):
        g.join_points = 10
    failed = failed_addrs(dbg_path)
    removed = _uniq(_lines(dbg_path, "removed"))
    comp = 0
    for k, a in enumerate(failed):
        if k >= 6:
            break
        if sum(1 for ln in removed if a in ln) >= 5:
            comp += 2
    g.completeness_points = min(comp, 10)
    acc = 0
    for a in failed:
        if sum(1 for ln in removed if a not in ln) == 20:
            acc += 2
        if acc > 9:
            break
    g.accuracy_points = min(acc, 10)
    return g


def grade_all(run_scenario_fn=None, testcases_dir: str = "testcases",
              workdir: str = ".") -> dict:
    """Grade the three shipped scenarios; mirrors Grader.sh's totals.

    ``run_scenario_fn(conf_path, workdir)`` must produce
    ``workdir/dbg.log`` for the given testcase (the grader recompiles
    and reruns the binary per scenario; we re-simulate per scenario).
    With the default ``run_scenario_fn=None`` the scenarios are served
    through the fleet service instead (:func:`grade_all_service`) —
    same totals, batched execution.
    """
    if run_scenario_fn is None:
        return grade_all_service(testcases_dir, workdir)
    dbg = os.path.join(workdir, "dbg.log")
    results = {}

    run_scenario_fn(os.path.join(testcases_dir, "singlefailure.conf"), workdir)
    results["singlefailure"] = grade_single(dbg)

    run_scenario_fn(os.path.join(testcases_dir, "multifailure.conf"), workdir)
    results["multifailure"] = grade_multi(dbg)

    run_scenario_fn(os.path.join(testcases_dir, "msgdropsinglefailure.conf"), workdir)
    results["msgdropsinglefailure"] = grade_single(
        dbg, join_pts=15, comp_pts=15, acc_pts=None)

    results["total"] = sum(r.points for r in results.values()
                           if isinstance(r, ScenarioGrade))
    return results


def _default_runner(conf: str, workdir: str) -> None:
    from .config import SimConfig
    from .core.sim import run_scenario
    run_scenario(SimConfig.from_conf(conf), outdir=workdir)


#: the three shipped scenarios, in Grader.sh order
SCENARIOS = ("singlefailure", "multifailure", "msgdropsinglefailure")


def grade_all_fleet(testcases_dir: str = "testcases",
                    workdir: str = ".") -> dict:
    """Grade the three shipped scenarios from ONE fleet run.

    The scenarios share a compiled shape (N=10, 700 ticks; their
    single/multi/drop differences are all Schedule data), so instead
    of three sequential trace runs they execute as a B=3
    :class:`~.core.fleet.FleetSimulation` — one vmapped program, one
    dispatch per chunk for all three course scenarios.  Per-lane
    events are bit-identical to the sequential runs
    (tests/test_fleet.py), so the grades are too; mirrors
    :func:`grade_all`'s totals exactly.
    """
    from .config import SimConfig
    from .core.fleet import FleetSimulation

    cfgs = [SimConfig.from_conf(os.path.join(testcases_dir, f"{s}.conf"))
            for s in SCENARIOS]
    fleet = FleetSimulation(cfgs[0]).run(configs=cfgs)
    dbg = os.path.join(workdir, "dbg.log")
    results = {}
    for name, lane in zip(SCENARIOS, fleet.lanes):
        lane.write_logs(workdir)
        if name == "singlefailure":
            results[name] = grade_single(dbg)
        elif name == "multifailure":
            results[name] = grade_multi(dbg)
        else:
            results[name] = grade_single(dbg, join_pts=15, comp_pts=15,
                                         acc_pts=None)
    results["total"] = sum(r.points for r in results.values()
                           if isinstance(r, ScenarioGrade))
    return results


def grade_all_service(testcases_dir: str = "testcases",
                      workdir: str = ".", service=None) -> dict:
    """Grade the three shipped scenarios through the fleet SERVICE.

    The grader is the serving layer's first real client: each scenario
    is submitted as a trace request to a :class:`~.service.FleetService`
    and graded from its handle's lane result.  The bucketer does the
    batching decision — single/multi share one compiled program (equal
    shape + segment plan), while msgdrop's shifted drop window lands
    in its own bucket (its segment-plan signature differs; the
    grid-kernel family bakes that window statically, and the service
    never assumes which engine path a bucket rides).  Per-lane events
    are bit-identical to solo runs (tests/test_service.py), so the
    totals mirror :func:`grade_all` exactly.
    """
    from .config import SimConfig
    from .service import FleetService

    svc = service if service is not None else FleetService(
        max_batch=len(SCENARIOS), pad_policy="none")
    handles = [svc.submit(SimConfig.from_conf(
        os.path.join(testcases_dir, f"{s}.conf")), mode="trace")
        for s in SCENARIOS]
    svc.drain()
    dbg = os.path.join(workdir, "dbg.log")
    results = {}
    for name, h in zip(SCENARIOS, handles):
        h.result().write_logs(workdir)
        if name == "multifailure":
            results[name] = grade_multi(dbg)
        elif name == "msgdropsinglefailure":
            results[name] = grade_single(dbg, join_pts=15, comp_pts=15,
                                         acc_pts=None)
        else:
            results[name] = grade_single(dbg)
    results["total"] = sum(r.points for r in results.values()
                           if isinstance(r, ScenarioGrade))
    return results


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(description="Grade the three scenarios "
                                 "(Grader.sh-equivalent checks)")
    ap.add_argument("--testcases", default="testcases")
    ap.add_argument("--workdir", default=".")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for the N=10 grading runs (default "
                         "cpu: grading is tiny and must not dial an "
                         "accelerator tunnel)")
    ap.add_argument("--log", default=None, metavar="DBG_LOG",
                    help="grade an existing dbg.log instead of running "
                         "the scenarios (use with --kind)")
    ap.add_argument("--kind", default="single",
                    choices=["single", "multi", "drop"],
                    help="scenario kind of --log")
    args = ap.parse_args(argv)

    if args.log is not None:
        if args.kind == "single":
            g = grade_single(args.log)
        elif args.kind == "multi":
            g = grade_multi(args.log)
        else:
            g = grade_single(args.log, join_pts=15, comp_pts=15, acc_pts=None)
        print(f"{args.log}: {g.points}/{g.max_points}  {g.detail}")
        return 0 if g.points == g.max_points else 1

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    # the three course scenarios go through the serving layer (the
    # grader is its first real client): bucketed by compiled shape +
    # segment plan, batched per bucket (grade_all_service)
    results = grade_all(None, args.testcases, args.workdir)
    for name, g in results.items():
        if isinstance(g, ScenarioGrade):
            print(f"{name}: join {g.join_points}/{g.join_max}  "
                  f"completeness {g.completeness_points}/{g.completeness_max}  "
                  f"accuracy {g.accuracy_points}/{g.accuracy_max}")
    print(f"Final grade {results['total']}")
    return 0 if results["total"] == 90 else 1


if __name__ == "__main__":
    raise SystemExit(main())
