"""Latency-under-load harness: the open-loop serving bench.

The closed-loop replay (service/replay.py) answers "how fast can the
service drain a fixed batch of work"; this module answers the question
the north star actually asks: **what latency does a request see at a
given offered load, and where does the service saturate?**  It drives
the pipelined scheduler with seeded open-loop arrival schedules
(service/traffic.py) at a swept ladder of offered loads and reports,
per load point, p50/p99 latency per priority class, per-class
deadline-miss rates, occupancy, shed counts, and how far submissions
fell behind schedule — plus the measured saturation point (the first
offered load the service cannot absorb).

Three probes, composed by :func:`load_openloop_bench` into the
``secondary.service_load_openloop`` BENCH entry:

* :func:`sweep` — wall-paced load ladder (fractions of a measured
  closed-loop capacity probe), >= 4 points, each a fresh service over
  process-cached programs so points don't share stats windows;
* :func:`slo_ab` — the same schedule served twice at one load,
  deadline-aware early flush ON vs OFF (identical classes and
  deadlines both legs): the miss-rate delta is the SLO scheduler's
  measured value, not a modeling claim;
* :func:`replay_check` — the determinism gate: one seed driven twice
  through VIRTUAL pacing (service clock = the schedule's virtual
  clock, harvest pinned off, wall estimate pinned), arrival and
  outcome digests must match run-for-run — load runs are replayable
  regression tests, exactly like chaos runs.

Fault-free load runs hold the chaos plane's completion discipline:
every handle must be terminal after the drain, and the only tolerated
failures are the typed load outcomes (DeadlineExceeded expiry,
ShedRejection at admission).  Anything else raises — an engine error
must never be laundered into a "miss rate".
"""

from __future__ import annotations

import time
from dataclasses import replace
from typing import Optional, Sequence

import numpy as np

from .replay import Template, grader_templates, overlay_templates
from .resilience import DeadlineExceeded
from .scheduler import FleetService
from .slo import SLOPolicy, default_slo
from .traffic import (TrafficPattern, VirtualClock, make_schedule,
                      outcome_digest, run_schedule)


def load_catalog(n: int = 512, ticks: int = 96) -> list[Template]:
    """The mixed scenario catalog the load plane serves: the grader
    tier (exact dense N=10 course scenarios) + the overlay scale tier
    (fail / churn / drop10) — the same six templates as the replay
    acceptance stream, arriving open-loop instead of all at once."""
    return grader_templates() + overlay_templates(n=n, ticks=ticks)


def warm_service(svc: FleetService, templates: Sequence[Template]) -> None:
    """Compile + execute every distinct template's bucket program once
    (also seeds the per-bucket wall EWMAs the early flush reads)."""
    done = set()
    for tpl in templates:
        if tpl.name in done:
            continue
        done.add(tpl.name)
        svc.warm(tpl.cfg, tpl.mode)


def probe_capacity_rps(templates: Sequence[Template],
                       n_requests: int = 48, max_batch: int = 8,
                       seed: int = 0, warm_lap: bool = True,
                       mesh=None,
                       pipeline_depth: Optional[int] = None) -> float:
    """Closed-loop burst probe: all ``n_requests`` at t=0, drain; the
    achieved completion rate is the service's max sustainable
    throughput for this catalog — the ladder's 1.0x anchor.  With
    ``warm_lap`` an untimed identical lap runs first (compilation and
    the first-lap trace/placement-cache costs are not steady-state
    serving, docs/PERF.md §11)."""
    pattern = TrafficPattern(kind="closed", rate_rps=float(n_requests))
    laps = (0, 1) if warm_lap else (1,)
    rate = 0.0
    for lap in laps:
        svc = FleetService(max_batch=max_batch, mesh=mesh,
                           pipeline_depth=pipeline_depth)
        warm_service(svc, templates)
        sched = make_schedule(templates, n_requests, pattern,
                              seed=seed + lap)
        handles, rec = run_schedule(svc, sched, pace="wall")
        done = sum(1 for h in handles if h is not None and h.done
                   and not h.failed)
        rate = done / rec["wall_s"]
    return rate


def measure_point(templates: Sequence[Template], n_requests: int,
                  rate_rps: float, seed: int, slo: SLOPolicy,
                  kind: str = "poisson", max_batch: int = 8,
                  max_wait_s: Optional[float] = 8.0,
                  early_flush: Optional[bool] = None,
                  tenant_quota: Optional[int] = None,
                  max_queue_depth: Optional[int] = None,
                  mesh=None,
                  pipeline_depth: Optional[int] = None) -> dict:
    """One wall-paced open-loop run at one offered load; returns the
    load point's row.  Raises on any non-terminal handle or any
    failure that is not a typed load outcome (deadline expiry /
    admission shed).  ``mesh`` serves the point from a lane mesh
    (``max_batch`` becomes per-device — pass ``total // D`` for
    equal-capacity comparisons against a D=1 point)."""
    eff_slo = slo if early_flush is None \
        else slo.with_early_flush(early_flush)
    pattern = TrafficPattern(kind=kind, rate_rps=rate_rps)
    sched = make_schedule(templates, n_requests, pattern, seed=seed,
                          class_mix=eff_slo.class_mix())
    svc = FleetService(max_batch=max_batch, max_wait_s=max_wait_s,
                       slo=eff_slo, tenant_quota=tenant_quota,
                       max_queue_depth=max_queue_depth, mesh=mesh,
                       pipeline_depth=pipeline_depth)
    # warm before the clock starts: programs are process-cached after
    # the capacity probe, but warm() also seeds the per-bucket wall
    # EWMAs the deadline-aware early flush reads — a cold estimate
    # would disable the SLO scheduler for the first dispatches
    warm_service(svc, templates)
    handles, rec = run_schedule(svc, sched, pace="wall")
    stats = svc.stats()

    submitted = [h for h in handles if h is not None]
    stranded = [h for h in submitted if not h.done]
    if stranded:
        raise RuntimeError(
            f"open-loop run left {len(stranded)} non-terminal handles "
            f"of {len(submitted)} (rate {rate_rps:.2f} rps, seed "
            f"{seed}); the drain guarantee is broken")
    bad = [h for h in submitted if h.failed
           and not isinstance(h.exception(), DeadlineExceeded)]
    if bad:
        raise RuntimeError(
            f"open-loop run had {len(bad)} non-deadline failures "
            f"(first: {bad[0].exception()!r}); engine errors must not "
            "be reported as load outcomes")

    completed = [h for h in submitted if h.done and not h.failed]
    expired = [h for h in submitted if h.failed]
    # per-class rows from the handles themselves (each point is a
    # fresh service, but handle-level accounting keeps the row
    # independent of stats windowing entirely)
    classes: dict[str, dict] = {}
    for a, h in zip(sched.arrivals, handles):
        c = classes.setdefault(a.priority, {
            "requests": 0, "completed": 0, "expired": 0, "shed": 0,
            "deadline_misses": 0, "_lat": []})
        c["requests"] += 1
        if h is None:
            c["shed"] += 1
            continue
        if h.failed:
            c["expired"] += 1
            c["deadline_misses"] += 1
            continue
        c["completed"] += 1
        c["_lat"].append(h.metrics.latency_s)
        if h.metrics.deadline_missed:
            c["deadline_misses"] += 1
    for c in classes.values():
        lat = np.asarray(c.pop("_lat"), dtype=np.float64)
        c["latency_p50_s"] = round(float(np.percentile(lat, 50)), 4) \
            if lat.size else 0.0
        c["latency_p99_s"] = round(float(np.percentile(lat, 99)), 4) \
            if lat.size else 0.0
        terminal = c["completed"] + c["expired"]
        c["deadline_miss_rate"] = \
            round(c["deadline_misses"] / terminal, 4) if terminal else 0.0

    lat_all = np.asarray([h.metrics.latency_s for h in completed],
                         dtype=np.float64)
    missed = sum(1 for h in completed if h.metrics.deadline_missed) \
        + len(expired)
    terminal = len(completed) + len(expired)
    return {
        "offered_rps": round(rate_rps, 3),
        "achieved_rps": round(len(completed) / rec["wall_s"], 3)
        if rec["wall_s"] > 0 else 0.0,
        "arrival_kind": kind,
        "requests": len(sched),
        "completed": len(completed),
        "expired": len(expired),
        "shed": len(rec["sheds"]),
        "latency_p50_s": round(float(np.percentile(lat_all, 50)), 4)
        if lat_all.size else 0.0,
        "latency_p99_s": round(float(np.percentile(lat_all, 99)), 4)
        if lat_all.size else 0.0,
        "deadline_miss_rate": round(missed / terminal, 4)
        if terminal else 0.0,
        "mean_occupancy": stats["mean_occupancy"],
        "pipeline_depth": stats["pipeline_depth"],
        "ring_stalls": stats["ring_stalls"],
        "slo_early_flushes": stats["slo_early_flushes"],
        "max_lag_s": round(rec["max_lag_s"], 3),
        "span_s": round(sched.span_s, 3),
        "wall_s": round(rec["wall_s"], 3),
        "classes": dict(sorted(classes.items())),
        "wfq_served": stats["wfq_served"],
    }


#: a load point saturates when it completes less than this fraction of
#: its offered rate...
SATURATION_FRAC = 0.9
#: ...AND its makespan overran the schedule span by this factor (a
#: backlog that outlived the arrivals).  The second condition matters:
#: every finite run pays a drain tail after the last arrival, and at
#: small request counts that tail alone pushes achieved below offered
#: even when the service is nowhere near saturated.
SATURATION_SPAN_RATIO = 1.2


def _saturated(row: dict) -> bool:
    return (row["achieved_rps"] < SATURATION_FRAC * row["offered_rps"]
            and row["wall_s"] > SATURATION_SPAN_RATIO * row["span_s"])


def sweep(templates: Sequence[Template], n_requests: int,
          capacity_rps: float, seed: int, slo: SLOPolicy,
          fracs: Sequence[float] = (0.25, 0.5, 0.75, 1.0, 1.5),
          **point_kw) -> dict:
    """The offered-load ladder: one :func:`measure_point` per fraction
    of the probed capacity (distinct seeds per point — distinct
    schedules, like the bench's distinct rep seeds), plus the measured
    saturation point: the first offered load the service could not
    absorb (:func:`_saturated` — completion rate below
    ``SATURATION_FRAC`` of offered AND the backlog outlived the
    arrival schedule)."""
    rows = []
    for i, f in enumerate(fracs):
        r = measure_point(templates, n_requests,
                          rate_rps=capacity_rps * f,
                          seed=seed + i, slo=slo, **point_kw)
        r["saturated"] = _saturated(r)
        rows.append(r)
    saturation = next((r["offered_rps"] for r in rows
                       if r["saturated"]), None)
    return {
        "capacity_probe_rps": round(capacity_rps, 3),
        "load_fracs": list(fracs),
        "points": rows,
        "saturation_offered_rps": saturation,
        "max_achieved_rps": max(r["achieved_rps"] for r in rows),
    }


def effective_saturation(row: dict) -> float:
    """A ladder's saturation point as a comparable number: the offered
    rps of the first saturated point, or +inf when the ladder never
    saturated (absorbing every offered load is strictly better than
    saturating at any finite one)."""
    sat = row.get("saturation_offered_rps")
    return float("inf") if sat is None else float(sat)


def depth_ladder(templates: Sequence[Template], n_probe: int,
                 n_point: int, seed: int, slo: SLOPolicy,
                 fracs: Sequence[float],
                 depths: Sequence[int] = (1, 2, 4),
                 max_batch: int = 8) -> dict:
    """The PR 17 headline measurement: the SAME open-loop ladder at
    pipeline depth 1 / 2 / 4.  One capacity probe (at depth 1) anchors
    the offered rates, and each point reuses the same seed across
    depths — identical arrival schedules, so the saturation shift is
    the depth's doing, not the schedule's.  Each row also records the
    depth's own closed-loop burst probe and the ring back-pressure
    (``ring_stalls``) the sweep's points accumulated."""
    cap = probe_capacity_rps(templates, n_requests=n_probe,
                             max_batch=max_batch, pipeline_depth=1)
    rows = []
    for d in depths:
        closed = probe_capacity_rps(templates, n_requests=n_probe,
                                    max_batch=max_batch,
                                    pipeline_depth=d)
        sw = sweep(templates, n_point, cap, seed=seed, slo=slo,
                   fracs=fracs, max_batch=max_batch, pipeline_depth=d)
        rows.append({
            "depth": d,
            "closed_loop_rps": round(closed, 3),
            "saturation_offered_rps": sw["saturation_offered_rps"],
            "max_achieved_rps": sw["max_achieved_rps"],
            "points": sw["points"],
        })
    return {"anchor_capacity_rps": round(cap, 3),
            "load_fracs": list(fracs), "rows": rows}


def slo_ab(templates: Sequence[Template], n_requests: int,
           rate_rps: float, seed: int, slo: SLOPolicy,
           ordering_ab: bool = True, wfq_ab: bool = True,
           wfq_weights=None, **point_kw) -> dict:
    """Deadline-aware batch formation ON vs OFF on the SAME schedule
    (same seed, same classes and deadlines — only the early-flush rule
    differs).  The report's ``improved`` is the acceptance gate:
    strictly fewer deadline misses with the SLO scheduler on.

    ``ordering_ab`` additionally runs the SAME schedule with
    deadline-aware DISPATCH ORDERING off (PR 8 satellite:
    ``SLOPolicy.class_ordering`` — ``pump()`` pops
    tightest-deadline-first instead of FIFO over buckets); the
    ``ordering`` block compares miss rates with ordering on (the
    early-flush ON leg, which carries it) vs off.  Recorded, not
    gated: at light load both legs can tie at zero misses.

    ``wfq_ab`` (PR 9 satellite) runs the SAME schedule once more with
    per-class WEIGHTED FAIR QUEUING (``SLOPolicy.weights``, default
    ``{"interactive": 8.0}``): the ``wfq`` block reports the
    interactive class's latency/miss under weighted vs
    tightest-deadline ordering plus each leg's per-class dispatched-
    lane shares (``wfq_served``) — the measured dispatch-share shift
    the knob buys.  Recorded, not gated, for the same light-load-tie
    reason.
    """
    on = measure_point(templates, n_requests, rate_rps, seed, slo,
                       early_flush=True, **point_kw)
    off = measure_point(templates, n_requests, rate_rps, seed, slo,
                        early_flush=False, **point_kw)
    out = {
        "offered_rps": round(rate_rps, 3),
        "on": on, "off": off,
        "miss_rate_on": on["deadline_miss_rate"],
        "miss_rate_off": off["deadline_miss_rate"],
        "improved": on["deadline_miss_rate"] < off["deadline_miss_rate"],
    }
    if wfq_ab:
        ic = "interactive" if "interactive" in slo.classes \
            else slo.default_class
        # explicit weights pass through unfiltered so SLOPolicy
        # validation rejects typo'd class names; the default targets
        # whichever class ``ic`` resolved to, so the weighted leg
        # always exercises a real weight
        weights = dict(wfq_weights) if wfq_weights is not None \
            else {ic: 8.0}
        wrow = measure_point(templates, n_requests, rate_rps, seed,
                             replace(slo, weights=weights),
                             early_flush=True, **point_kw)
        out["wfq"] = {
            "weights": weights,
            "miss_rate_weighted": wrow["deadline_miss_rate"],
            "miss_rate_unweighted": on["deadline_miss_rate"],
            "class_miss_weighted":
                wrow["classes"].get(ic, {}).get("deadline_miss_rate"),
            "class_miss_unweighted":
                on["classes"].get(ic, {}).get("deadline_miss_rate"),
            "class_p50_weighted":
                wrow["classes"].get(ic, {}).get("latency_p50_s"),
            "class_p50_unweighted":
                on["classes"].get(ic, {}).get("latency_p50_s"),
            "served_weighted": wrow["wfq_served"],
            "served_unweighted": on["wfq_served"],
        }
    if ordering_ab:
        no_order = measure_point(
            templates, n_requests, rate_rps, seed,
            replace(slo, class_ordering=False), early_flush=True,
            **point_kw)
        out["ordering"] = {
            "miss_rate_ordered": on["deadline_miss_rate"],
            "miss_rate_fifo": no_order["deadline_miss_rate"],
            "improved": on["deadline_miss_rate"]
            < no_order["deadline_miss_rate"],
            "no_worse": on["deadline_miss_rate"]
            <= no_order["deadline_miss_rate"],
        }
    return out


def replay_check(templates: Sequence[Template], n_requests: int,
                 rate_rps: float, seed: int, slo: SLOPolicy,
                 max_batch: int = 8,
                 max_wait_s: Optional[float] = 8.0,
                 assumed_wall_s: float = 0.5, runs: int = 2) -> dict:
    """The load plane's replay gate: the same seed driven ``runs``
    times through VIRTUAL pacing must produce identical arrival AND
    outcome digests.  Determinism needs three pins (all documented in
    service/traffic.py): the service clock is the schedule's virtual
    clock, the idle harvest is off (``pump_harvest=False``), and the
    early-flush wall estimate is the policy's pinned value rather than
    a measured EWMA."""
    det_slo = replace(slo, assumed_dispatch_wall_s=assumed_wall_s)
    digests = []
    for _ in range(runs):
        vc = VirtualClock()
        svc = FleetService(max_batch=max_batch, max_wait_s=max_wait_s,
                           slo=det_slo, clock=vc, sleep=vc.sleep,
                           pump_harvest=False)
        warm_service(svc, templates)
        sched = make_schedule(templates, n_requests,
                              TrafficPattern(rate_rps=rate_rps),
                              seed=seed, class_mix=det_slo.class_mix())
        handles, rec = run_schedule(svc, sched, pace="virtual",
                                    clock=vc)
        digests.append((sched.digest(),
                        outcome_digest(sched, handles, rec["sheds"])))
    return {
        "seed": seed,
        "runs": runs,
        "arrival_digest": digests[0][0],
        "outcome_digest": digests[0][1],
        "deterministic": len(set(digests)) == 1,
    }


def load_openloop_bench(smoke: bool = False, seed: int = 20260804,
                        now=time.perf_counter) -> dict:
    """The whole open-loop story as one BENCH entry: capacity probe ->
    load ladder with saturation -> SLO A/B at a partial-batch load ->
    the virtual-clock determinism gate.  The caller (bench.py) adds
    env provenance."""
    if smoke:
        templates = load_catalog(n=256, ticks=48)
        n_probe, n_point = 16, 24
        fracs = (0.3, 0.75, 1.1, 1.6)
    else:
        templates = load_catalog(n=512, ticks=96)
        n_probe, n_point = 48, 90
        fracs = (0.25, 0.5, 0.75, 1.0, 1.5)
    slo = default_slo()
    t0 = now()
    cap = probe_capacity_rps(templates, n_requests=n_probe)
    sw = sweep(templates, n_point, cap, seed=seed, slo=slo, fracs=fracs)
    # the A/B load: low enough that buckets stay partial (early flush
    # is the only way a latency-class request makes its deadline),
    # high enough that the stream is not trivial
    ab = slo_ab(templates, n_point, rate_rps=0.4 * cap, seed=seed + 100,
                slo=slo)
    rc = replay_check(templates, max(12, n_point // 3),
                      rate_rps=0.5 * cap, seed=seed + 200, slo=slo)
    # the gates are ENFORCED, not just recorded: a bench json must not
    # quietly carry a regressed acceptance property
    if not rc["deterministic"]:
        raise RuntimeError(
            "open-loop replay check failed: the same seed produced "
            "different arrival/outcome digests across two virtual-"
            "paced runs — the load plane lost its determinism pins")
    if not smoke and not ab["improved"]:
        # smoke streams (24 requests over a fast catalog) are too
        # small to miss deadlines at all, so both legs tie at 0 there;
        # at full scale a tie or inversion is a real SLO regression
        raise RuntimeError(
            f"SLO A/B regression: deadline-miss rate with early flush "
            f"ON ({ab['miss_rate_on']}) is not strictly below OFF "
            f"({ab['miss_rate_off']}) at {ab['offered_rps']} rps")
    # the depth sweep (PR 17): the same ladder at pipeline depth
    # 1/2/4 — the headline gate is that depth 2 holds off saturation
    # at least as long as depth 1 (enforced on full runs; smoke
    # ladders are too small to saturate meaningfully)
    ds = depth_ladder(templates, n_probe, max(12, n_point // 3),
                      seed=seed + 400, slo=slo, fracs=fracs)
    by_depth = {r["depth"]: r for r in ds["rows"]}
    if not smoke and 1 in by_depth and 2 in by_depth \
            and effective_saturation(by_depth[2]) \
            < effective_saturation(by_depth[1]):
        raise RuntimeError(
            f"depth-sweep regression: depth-2 saturates at "
            f"{by_depth[2]['saturation_offered_rps']} rps, below "
            f"depth-1's {by_depth[1]['saturation_offered_rps']} — "
            f"per-bucket rings must not LOWER the saturation point")
    entry = {
        "pattern": "poisson",
        "slo_classes": {name: {"deadline_s": c.deadline_s,
                               "weight": c.weight}
                        for name, c in slo.classes.items()},
        **sw,
        "slo_ab": ab,
        "replay_check": rc,
        "depth_sweep": ds,
        "bench_wall_s": round(now() - t0, 1),
    }
    # lane-mesh load point (PR 8 satellite): the knee-load point once
    # more, served from a D=2 lane mesh at EQUAL total capacity
    # (max_batch halves per device) — recorded only when virtual
    # devices are live (XLA_FLAGS forces them; plain CPU runs have 1)
    import jax
    if jax.device_count() >= 2:
        from ..parallel.fleet_mesh import make_lane_mesh
        n_pt = max(12, n_point // 3)
        mesh_row = measure_point(
            templates, n_pt, rate_rps=0.75 * cap, seed=seed + 300,
            slo=slo, max_batch=4, mesh=make_lane_mesh(2))
        entry["mesh_point"] = {"devices": 2, "max_batch_per_device": 4,
                               **mesh_row}
    return entry


# ---- compile-surface bench (PR 16 tentpole) --------------------------
#
# The scenario grammar (models/scenarios.py, 25 families over eight
# worlds) jittered per request drives the EXACT bucket key toward one
# fresh XLA build per request; canonical bucketing
# (service/canonical.py) must collapse that — measured, not assumed.
# The bench drives the SAME mixed schedule through a baseline
# (canonicalize=False) service lap, a cold canonical lap, and a warm
# canonical lap, and gates on: per-request BIT-IDENTITY between the
# laps (the exact lap is the solo-equivalent reference; a sample is
# additionally checked against direct solo execution), ZERO builds on
# the warm lap, and (full runs) a >= 3x fresh-build collapse.

#: dense phase-window jitter stays within one CHECKPOINT_GRID_TICKS
#: cell on most draws (so quantization gets to collapse it) but
#: occasionally crosses a grid line (so class splits are exercised too)
_JITTER_TICKS = 5


def jitter_request(cfg, rng):
    """One grammar request, jittered the way a real mixed stream is:
    peer count off the power-of-two rungs, phase windows off the grid,
    world parameters (drop probability, byz boost, latency, wave
    shape) perturbed per request.  Overlay configs pass through —
    their bucket is exact by design and seed jitter alone keeps it
    warm.  Every jitter axis is one the canonical key either absorbs
    (operands, ladder, quantization) or legitimately splits on
    (grid-line crossings, drop-on real n)."""
    if cfg.model == "overlay":
        return cfg
    from ..service.canonical import ladder_rung
    rung = ladder_rung(cfg.n)
    kw = {"max_nnb": int(rng.integers(rung // 2 + 2, cfg.n + 1))}
    j = lambda: int(rng.integers(0, _JITTER_TICKS))

    def win(lo, hi):
        lo2 = lo + j()
        return lo2, max(lo2 + 2, hi - j())
    if cfg.drop_msg:
        kw["msg_drop_prob"] = round(
            float(cfg.msg_drop_prob * rng.uniform(0.6, 1.4)), 4)
        kw["drop_open_tick"], kw["drop_close_tick"] = \
            win(cfg.drop_open_tick, cfg.drop_close_tick)
    if cfg.partition_groups >= 2:
        kw["partition_open_tick"], kw["partition_close_tick"] = \
            win(cfg.partition_open_tick, cfg.partition_close_tick)
    if cfg.flap_rate > 0 and cfg.flap_open_tick >= 0:
        # -1/-1 means the default (total-derived) flap window; leave it
        kw["flap_open_tick"], kw["flap_close_tick"] = \
            win(cfg.flap_open_tick, cfg.flap_close_tick)
    if not cfg.single_failure:
        kw["wave_tick"] = cfg.wave_tick + j()
        kw["wave_size"] = max(2, cfg.wave_size - int(rng.integers(0, 2)))
    elif cfg.fail_tick < cfg.total_ticks:
        kw["fail_tick"] = cfg.fail_tick + j()
    if cfg.byz_rate > 0:
        kw["byz_boost"] = max(2, cfg.byz_boost + int(rng.integers(-2, 3)))
    if cfg.link_latency > 0:
        kw["link_latency"] = max(1, cfg.link_latency
                                 + int(rng.integers(-1, 2)))
    return cfg.replace(**kw)


def compile_surface_schedule(n_requests: int, seed: int,
                             families=None) -> list:
    """The mixed composed-world schedule: ``n_requests`` configs drawn
    family-round-robin from the scenario grammar, each jittered by
    :func:`jitter_request` under one seeded rng — deterministic, so
    baseline and canonical laps serve the byte-identical stream."""
    from ..models.scenarios import CATALOG
    fams = [CATALOG[f] for f in (families or sorted(CATALOG))]
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_requests):
        fam = fams[i % len(fams)]
        out.append(jitter_request(fam.build(seed + i), rng))
    return out


def _surface_lap(svc: "FleetService", cfgs) -> tuple:
    """Submit the whole schedule, drain, return (digests, builds)."""
    from ..core.tick import run_build_count
    from ..models.scenarios import _lane_digest
    b0 = run_build_count()
    handles = [svc.submit(c, mode="trace") for c in cfgs]
    svc.drain()
    digests = [_lane_digest(c, h.result())
               for c, h in zip(cfgs, handles)]
    return digests, run_build_count() - b0


def compile_surface_bench(smoke: bool = False, seed: int = 20260807,
                          n_requests: Optional[int] = None,
                          max_batch: int = 4,
                          solo_every: int = 10,
                          now=time.perf_counter) -> dict:
    """Measure the compile-surface collapse on a jittered mixed
    schedule; the ``secondary.compile_surface`` BENCH entry.

    Three laps over the byte-identical schedule: baseline exact
    buckets (the pre-canonicalization compile surface), cold canonical
    buckets, warm canonical buckets (same service, same schedule
    again).  Gates enforced in-line, not just recorded:

    * every request's canonical result digest equals its baseline
      (exact-bucket) digest, and a deterministic sample is ALSO
      checked against direct solo execution — bit-identity is the
      honesty condition of the whole scheme;
    * the warm lap observes ZERO fresh builds (the steady-state
      serving claim);
    * full runs only: fresh builds collapse by >= 3x cold (smoke
      schedules are too small to gate a ratio on).
    """
    from ..core.tick import run_build_count
    from ..models.scenarios import CATALOG, _lane_digest
    if smoke:
        # the eight cheapest dense families still span drop / window /
        # operand jitter; 48 requests keep the baseline lap's build
        # bill (~one per request, the point) under a smoke budget
        families = ["dense_partition_blip", "dense_asym_drop",
                    "dense_wave", "dense_zombie", "dense_flapping",
                    "dense_latency", "dense_composed_part_flap",
                    "dense_composed_latency_flap"]
        n = 48 if n_requests is None else n_requests
    else:
        families = sorted(CATALOG)
        n = 200 if n_requests is None else n_requests
    cfgs = compile_surface_schedule(n, seed, families)
    t0 = now()

    from .bucket import bucket_key
    from .canonical import canonical_bucket_key
    exact_keys = {bucket_key(c, "trace") for c in cfgs}
    canon_keys = {canonical_bucket_key(c, "trace") for c in cfgs}

    base_svc = FleetService(max_batch=max_batch)
    base_digests, base_builds = _surface_lap(base_svc, cfgs)
    t_base = now()

    canon_svc = FleetService(max_batch=max_batch, canonicalize=True)
    canon_digests, canon_builds = _surface_lap(canon_svc, cfgs)
    t_cold = now()
    stats_cold = canon_svc.stats()["cache"]
    hits0 = stats_cold["hits"] + stats_cold["misses"]

    warm_digests, warm_builds = _surface_lap(canon_svc, cfgs)
    stats_warm = canon_svc.stats()["cache"]
    lap2 = (stats_warm["hits"] + stats_warm["misses"]) - hits0
    warm_hit_rate = round(
        (stats_warm["hits"] - stats_cold["hits"]) / lap2, 4) \
        if lap2 else 0.0

    # ---- gates ----
    bad = [i for i, (a, b) in enumerate(zip(base_digests, canon_digests))
           if a != b]
    bad += [i for i, (a, b) in enumerate(zip(base_digests, warm_digests))
            if a != b]
    if bad:
        raise RuntimeError(
            f"canonical serving diverged from exact on request(s) "
            f"{sorted(set(bad))[:8]} of {n} — bit-identity is the "
            "precondition of bucket canonicalization")
    from .resilience import solo_execute
    solo_checked = 0
    for i in range(0, n, max(1, solo_every)):
        d = _lane_digest(cfgs[i], solo_execute(cfgs[i], "trace"))
        if d != canon_digests[i]:
            raise RuntimeError(
                f"canonical result for request {i} diverged from its "
                f"direct solo run ({d} != {canon_digests[i]})")
        solo_checked += 1
    if warm_builds != 0:
        raise RuntimeError(
            f"warm canonical lap observed {warm_builds} fresh builds; "
            "steady-state serving must not recompile")
    collapse = round(base_builds / canon_builds, 2) \
        if canon_builds else float(base_builds)
    if not smoke and collapse < 3.0:
        raise RuntimeError(
            f"compile-surface collapse {collapse}x is below the 3x "
            f"gate (baseline {base_builds} builds, canonical "
            f"{canon_builds}) — canonicalization regressed")

    classes = canon_svc.cache.class_map()
    return {
        "requests": n,
        "families": len(families),
        "smoke": smoke,
        "buckets_exact": len(exact_keys),
        "buckets_canonical": len(canon_keys),
        "bucket_collapse_x": round(len(exact_keys)
                                   / max(len(canon_keys), 1), 2),
        "builds_baseline": int(base_builds),
        "builds_canonical": int(canon_builds),
        "build_collapse_x": collapse,
        "warm_builds": int(warm_builds),
        "warm_hit_rate": warm_hit_rate,
        "classes": len(classes),
        "max_class_members": max(
            (len(v["members"]) for v in classes.values()), default=0),
        "parity_ok": True,
        "solo_checked": solo_checked,
        "baseline_wall_s": round(t_base - t0, 1),
        "canonical_wall_s": round(t_cold - t_base, 1),
        "bench_wall_s": round(now() - t0, 1),
    }
