"""Compiled-program cache: one FleetSimulation per bucket key.

The expensive artifacts — jitted whole-fleet programs — already live
in the process-wide ``core.fleet._FLEET_FN_CACHE`` keyed by (shape
key, segment-plan signature, batch geometry), and every build there
moves ``core.tick.run_build_count``.  This cache adds the serving
view of the same thing: bucket key -> the FleetSimulation handle that
owns the bucket's dispatches, plus hit/miss/build counters so the
scheduler can report cache behavior per dispatch ("a 20-request mixed
trace builds at most once per distinct bucket key",
tests/test_service.py::test_mixed_trace_builds_once_per_bucket).
"""

from __future__ import annotations

from typing import Optional

from ..config import SimConfig
from ..core.fleet import FleetSimulation
from ..core.tick import run_build_count


class ProgramCache:
    """bucket key -> :class:`~..core.fleet.FleetSimulation`."""

    def __init__(self, block_size: int = 128,
                 chunk_ticks: Optional[int] = None):
        self._block_size = block_size
        self._chunk_ticks = chunk_ticks
        self._sims: dict = {}
        self.hits = 0
        self.misses = 0
        self._builds0 = run_build_count()

    def get(self, key: tuple, cfg: SimConfig) -> FleetSimulation:
        """The bucket's fleet handle (created on first use).

        ``cfg`` seeds the handle's shape on a miss; later calls with
        any same-bucket config return the same handle.
        """
        sim = self._sims.get(key)
        if sim is None:
            self.misses += 1
            sim = FleetSimulation(cfg, block_size=self._block_size,
                                  chunk_ticks=self._chunk_ticks)
            self._sims[key] = sim
        else:
            self.hits += 1
        return sim

    @property
    def builds(self) -> int:
        """Whole-run builds observed since this cache was created.

        A process-wide ``run_build_count`` delta: it counts this
        service's builds plus any other compilation activity in the
        process — exact when the service is the only driver (the smoke
        replay), an upper bound otherwise.
        """
        return run_build_count() - self._builds0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {"buckets": len(self._sims), "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hit_rate, 4),
                "builds": self.builds}
