"""Compiled-program cache: one FleetSimulation per bucket key.

The expensive artifacts — jitted whole-fleet programs — already live
in the process-wide ``core.fleet._FLEET_FN_CACHE`` keyed by (shape
key, segment-plan signature, mesh slot, batch geometry), and every
build there moves ``core.tick.run_build_count``.  This cache adds the
serving view of the same thing: bucket key -> the FleetSimulation
handle that owns the bucket's dispatches, plus hit/miss/build
counters so the scheduler can report cache behavior per dispatch ("a
20-request mixed trace builds at most once per distinct bucket key",
tests/test_service.py::test_mixed_trace_builds_once_per_bucket).

Two serving-scale concerns live here rather than in core/fleet.py:

* **Mesh identity.**  A cache constructed over a lane mesh
  (parallel/fleet_mesh.py) hands out
  :class:`~..parallel.fleet_mesh.MeshFleetSimulation` handles, whose
  compiled programs carry the mesh descriptor in the process-wide
  ``_FLEET_FN_CACHE`` keys — a device-count change can never be
  served a stale single-device (or wrong-width) program
  (tests/test_service.py::test_mesh_device_count_misses_program_cache).
* **A bound.**  Bucket keys multiply under a mesh sweep (same shapes
  x device counts) and under long heterogeneous streams, and each
  bucket pins jitted executables.  ``max_entries`` bounds the cache
  with LRU eviction; evicting a bucket also drops its compiled
  programs from the process caches (``FleetSimulation.
  evict_programs``), so the bound frees real memory, not just the
  thin handle.  Note the process caches are shared: evicting a shape
  another driver (e.g. the grader) still uses costs that driver one
  rebuild — correctness is never affected.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from ..config import SimConfig
from ..core.fleet import FleetSimulation
from ..core.tick import run_build_count


class ProgramCache:
    """bucket key -> :class:`~..core.fleet.FleetSimulation` (or the
    mesh subclass when constructed with ``mesh=``), LRU-bounded."""

    def __init__(self, block_size: int = 128,
                 chunk_ticks: Optional[int] = None, mesh=None,
                 max_entries: Optional[int] = 64):
        if max_entries is not None and max_entries < 1:
            raise ValueError(f"max_entries must be >= 1 or None, "
                             f"got {max_entries}")
        self._block_size = block_size
        self._chunk_ticks = chunk_ticks
        self._mesh = mesh
        self.max_entries = max_entries
        self._sims: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.mesh_rebinds = 0
        self._builds0 = run_build_count()

    def _make_sim(self, cfg: SimConfig) -> FleetSimulation:
        if self._mesh is not None:
            from ..parallel.fleet_mesh import MeshFleetSimulation
            return MeshFleetSimulation(cfg, self._mesh,
                                       block_size=self._block_size,
                                       chunk_ticks=self._chunk_ticks)
        return FleetSimulation(cfg, block_size=self._block_size,
                               chunk_ticks=self._chunk_ticks)

    def get(self, key: tuple, cfg: SimConfig) -> FleetSimulation:
        """The bucket's fleet handle (created on first use).

        ``cfg`` seeds the handle's shape on a miss; later calls with
        any same-bucket config return the same handle.  Entries are
        touched LRU-wise; inserting past ``max_entries`` evicts the
        least recently used bucket AND its compiled programs.  The
        cache serves ONE mesh at a time (set at construction;
        :meth:`rebind_mesh` moves it down the degradation ladder and
        drops every handle), so the bucket key alone identifies an
        entry here; cross-mesh staleness is impossible anyway because
        the handles' compiled programs carry the mesh slot in their
        own process-cache keys (core/fleet.py ``_mesh_entry``).
        """
        sim = self._sims.get(key)
        if sim is None:
            self.misses += 1
            sim = self._make_sim(cfg)
            self._sims[key] = sim
            if self.max_entries is not None \
                    and len(self._sims) > self.max_entries:
                _, old = self._sims.popitem(last=False)
                old.evict_programs()
                self.evictions += 1
        else:
            self.hits += 1
            self._sims.move_to_end(key)
        return sim

    def rebind_mesh(self, mesh) -> int:
        """Graceful mesh degradation (PR 5): re-point the cache at a
        smaller mesh (or ``None`` for single-device) after a device
        loss.  Every bucket handle is dropped — their compiled
        programs target a mesh that no longer exists — and each
        handle's programs are evicted from the process caches
        per-handle-exactly (``FleetSimulation.evict_programs``), so
        sibling buckets owned by OTHER drivers keep theirs.  The next
        ``get`` per bucket rebuilds on the new mesh through the same
        mesh-keyed cache keys that already made cross-mesh staleness
        impossible.  Returns how many bucket handles were dropped."""
        n = len(self._sims)
        for sim in self._sims.values():
            sim.evict_programs()
        self._sims.clear()
        self._mesh = mesh
        self.mesh_rebinds += 1
        return n

    @property
    def builds(self) -> int:
        """Whole-run builds observed since this cache was created.

        A process-wide ``run_build_count`` delta: it counts this
        service's builds plus any other compilation activity in the
        process — exact when the service is the only driver (the smoke
        replay), an upper bound otherwise.
        """
        return run_build_count() - self._builds0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {"buckets": len(self._sims), "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hit_rate, 4),
                "builds": self.builds,
                "evictions": self.evictions,
                "mesh_rebinds": self.mesh_rebinds,
                "max_entries": self.max_entries,
                "devices": (self._mesh.devices.size
                            if self._mesh is not None else 1)}
