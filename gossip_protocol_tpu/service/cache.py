"""Compiled-program cache: one FleetSimulation per bucket key.

The expensive artifacts — jitted whole-fleet programs — already live
in the process-wide ``core.fleet._FLEET_FN_CACHE`` keyed by (shape
key, segment-plan signature, mesh slot, batch geometry), and every
build there moves ``core.tick.run_build_count``.  This cache adds the
serving view of the same thing: bucket key -> the FleetSimulation
handle that owns the bucket's dispatches, plus hit/miss/build
counters so the scheduler can report cache behavior per dispatch ("a
20-request mixed trace builds at most once per distinct bucket key",
tests/test_service.py::test_mixed_trace_builds_once_per_bucket).

Two serving-scale concerns live here rather than in core/fleet.py:

* **Mesh identity.**  A cache constructed over a lane mesh
  (parallel/fleet_mesh.py) hands out
  :class:`~..parallel.fleet_mesh.MeshFleetSimulation` handles, whose
  compiled programs carry the mesh descriptor in the process-wide
  ``_FLEET_FN_CACHE`` keys — a device-count change can never be
  served a stale single-device (or wrong-width) program
  (tests/test_service.py::test_mesh_device_count_misses_program_cache).
* **A bound.**  Bucket keys multiply under a mesh sweep (same shapes
  x device counts) and under long heterogeneous streams, and each
  bucket pins jitted executables.  ``max_entries`` bounds the cache
  with LRU eviction; evicting a bucket also drops its compiled
  programs from the process caches (``FleetSimulation.
  evict_programs``), so the bound frees real memory, not just the
  thin handle.  Note the process caches are shared: evicting a shape
  another driver (e.g. the grader) still uses costs that driver one
  rebuild — correctness is never affected.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from ..config import SimConfig
from ..core.fleet import FleetSimulation
from ..core.tick import run_build_count


class ProgramCache:
    """bucket key -> :class:`~..core.fleet.FleetSimulation` (or the
    mesh subclass when constructed with ``mesh=``), LRU-bounded."""

    def __init__(self, block_size: int = 128,
                 chunk_ticks: Optional[int] = None, mesh=None,
                 max_entries: Optional[int] = 64,
                 canon_rung_multiple: int = 1):
        if max_entries is not None and max_entries < 1:
            raise ValueError(f"max_entries must be >= 1 or None, "
                             f"got {max_entries}")
        self._block_size = block_size
        self._chunk_ticks = chunk_ticks
        self._mesh = mesh
        # the pad-ladder snap for canonical handles: the service's
        # FULL-STRENGTH peer count, fixed for the cache's lifetime so
        # canonical keys (and class membership) survive elastic
        # peer-shard shrink — rebind_mesh deliberately does NOT touch
        # it (service/canonical.py ladder_rung)
        self._canon_rung_multiple = int(canon_rung_multiple)
        self.max_entries = max_entries
        # entries are keyed (mesh descriptor, bucket key): rebinding
        # the mesh RE-KEYS the cache — handles (and their compiled
        # programs) built for other rungs of the elasticity ladder are
        # retained under their own descriptor, so a shrink -> grow
        # cycle finds the original mesh's programs warm instead of
        # recompiling them (PR 8; the LRU bound still caps the total)
        self._sims: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.mesh_rebinds = 0
        self.rekey_hits = 0
        self._builds0 = run_build_count()
        # canonical observability (PR 16): canonical bucket key ->
        # {"hits": dispatches served, "members": exact bucket keys
        # that would each have been their OWN bucket pre-canonical} —
        # the collapse ratio len(members)/1 per class is the whole
        # point of the pad-ladder, so it must be measurable here
        self._classes: OrderedDict = OrderedDict()

    def _make_sim(self, cfg: SimConfig,
                  canonical: bool = False) -> FleetSimulation:
        if canonical:
            if self._mesh is not None:
                from ..parallel.fleet_mesh import \
                    CanonicalMeshFleetSimulation
                return CanonicalMeshFleetSimulation(
                    cfg, self._mesh, block_size=self._block_size,
                    chunk_ticks=self._chunk_ticks,
                    rung_multiple=self._canon_rung_multiple)
            from ..core.fleet import CanonicalFleetSimulation
            return CanonicalFleetSimulation(
                cfg, block_size=self._block_size,
                chunk_ticks=self._chunk_ticks)
        if self._mesh is not None:
            from ..parallel.fleet_mesh import MeshFleetSimulation
            return MeshFleetSimulation(cfg, self._mesh,
                                       block_size=self._block_size,
                                       chunk_ticks=self._chunk_ticks)
        return FleetSimulation(cfg, block_size=self._block_size,
                               chunk_ticks=self._chunk_ticks)

    def _desc(self):
        """Hashable identity of the CURRENT mesh (None: no mesh)."""
        if self._mesh is None:
            return None
        from ..parallel.fleet_mesh import mesh_descriptor
        return mesh_descriptor(self._mesh)

    def get(self, key: tuple, cfg: SimConfig,
            members=None) -> FleetSimulation:
        """The bucket's fleet handle (created on first use).

        ``cfg`` seeds the handle's shape on a miss; later calls with
        any same-bucket config return the same handle.  Entries are
        touched LRU-wise; inserting past ``max_entries`` evicts the
        least recently used entry AND its compiled programs.  The
        cache serves ONE mesh at a time (set at construction;
        :meth:`rebind_mesh` moves it along the elasticity ladder), but
        entries are keyed ``(mesh descriptor, bucket key)``: handles
        built for OTHER rungs are retained — a grow back to a
        previously-served mesh re-keys straight to its warm programs.
        Cross-mesh staleness is impossible either way because the
        handles' compiled programs carry the mesh slot in their own
        process-cache keys (core/fleet.py ``_mesh_entry``).

        A ``"canon"``-leading ``key`` (service/canonical.py) creates a
        :class:`~..core.fleet.CanonicalFleetSimulation` handle serving
        the whole equivalence class; ``members`` is then the batch's
        EXACT bucket keys (one per lane config), recorded per class so
        :meth:`stats` can report the measured collapse — how many
        would-have-been-their-own buckets each canonical program
        absorbed.
        """
        canonical = bool(key) and key[0] == "canon"
        if canonical:
            cls = self._classes.setdefault(
                key, {"hits": 0, "members": set()})
            cls["hits"] += 1
            if members is not None:
                cls["members"].update(members)
        full = (self._desc(), key)
        sim = self._sims.get(full)
        if sim is None:
            self.misses += 1
            sim = self._make_sim(cfg, canonical=canonical)
            self._sims[full] = sim
            if self.max_entries is not None \
                    and len(self._sims) > self.max_entries:
                _, old = self._sims.popitem(last=False)
                old.evict_programs()
                self.evictions += 1
        else:
            self.hits += 1
            self._sims.move_to_end(full)
        return sim

    def rebind_mesh(self, mesh, evict: bool = False) -> int:
        """Move the cache along the elasticity ladder (PR 5 shrink /
        PR 8 grow): re-point it at a different mesh (or ``None`` for
        single-device).  Entries are RE-KEYED, not dropped — the
        ladder's other rungs keep their handles and compiled programs
        under their own mesh descriptor, so a shrink -> grow cycle
        serves the restored mesh from warm programs (zero rebuilds,
        tests/test_elastic.py) while the LRU bound still caps total
        retention.  ``evict=True`` restores the PR-5 behavior — drop
        everything and evict the programs per-handle-exactly — for
        deployments where the lost device's executables must actually
        be freed (a REAL device death; on this image devices are
        virtual and never die).  Returns how many handles were
        dropped (0 when re-keying)."""
        n = 0
        if evict:
            n = len(self._sims)
            for sim in self._sims.values():
                sim.evict_programs()
            self._sims.clear()
        self._mesh = mesh
        # handles already cached under the NEW descriptor were re-keyed
        # back into service by this rebind (the shrink -> grow payoff)
        self.rekey_hits += sum(1 for (d, _) in self._sims
                               if d == self._desc())
        self.mesh_rebinds += 1
        return n

    def keys(self) -> tuple:
        """The current ``(mesh descriptor, bucket key)`` entries, LRU
        order (oldest first).  Read-only observability: crash
        recovery (store/recovery.py) journals how many bucket handles
        its re-warm pass materialized, and tests assert the recovered
        cache covers every re-admitted bucket."""
        return tuple(self._sims)

    @property
    def builds(self) -> int:
        """Whole-run builds observed since this cache was created.

        A process-wide ``run_build_count`` delta: it counts this
        service's builds plus any other compilation activity in the
        process — exact when the service is the only driver (the smoke
        replay), an upper bound otherwise.
        """
        return run_build_count() - self._builds0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def class_map(self) -> dict:
        """canonical bucket key -> {"hits", "members"} (members is the
        SET of exact bucket keys served from the class — each one a
        fresh XLA build pre-canonicalization, one build now)."""
        return {k: {"hits": v["hits"],
                    "members": frozenset(v["members"])}
                for k, v in self._classes.items()}

    def stats(self) -> dict:
        classes = {
            repr(k): {"hits": v["hits"], "members": len(v["members"])}
            for k, v in self._classes.items()}
        return {"buckets": len(self._sims), "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hit_rate, 4),
                "builds": self.builds,
                "evictions": self.evictions,
                "mesh_rebinds": self.mesh_rebinds,
                "rekey_hits": self.rekey_hits,
                "max_entries": self.max_entries,
                "classes": classes,
                "class_member_buckets": sum(
                    len(v["members"]) for v in self._classes.values()),
                "devices": (self._mesh.devices.size
                            if self._mesh is not None else 1)}
