"""Continuous-batching request scheduler for simulation serving.

The engine side of serving landed in core/fleet.py: B same-shape
simulations through ONE compiled program, ~3.4x the wall of B=8
sequential runs on this CPU image (docs/PERF.md §8).  What was missing
is the layer every inference stack puts above such an engine (Orca's
iteration-level scheduler, vLLM's waiting/running queues): something
that accepts a *stream* of heterogeneous requests and keeps the
batched engine fed.  This module is that layer, sized to this
framework's unit of work — a whole simulation run, not a decode step,
so batches form per request stream rather than per iteration:

* **admission** — ``submit()`` validates the mode, stamps the request,
  and enqueues it under its shape bucket (service/bucket.py: shape
  key + segment-plan signature + mode); heterogeneous streams coexist
  as parallel queues rather than poisoning one batch.
* **flush policies** — a bucket dispatches when it has ``max_batch``
  requests (the B≈8-16 knee of the CPU batching curve, PERF §8), when
  its oldest request has waited ``max_wait_s`` (bounded latency under
  trickle traffic), or when ``flush()``/``drain()``/``result()``
  forces it.
* **padding** — a partial batch is padded to the bucket's compiled
  width with inert filler lanes (replicas of the bucket's first
  config) so one program per bucket serves every dispatch; filler is
  masked out device-side and never unstacked (core/fleet.py
  ``n_real``), so results stay bit-identical to solo runs.
* **program cache** — bucket key -> FleetSimulation (service/cache.py)
  with hit/miss/build counters over ``core.tick.run_build_count``.
* **metrics** — per-request queue wait / run wall / latency, per-
  dispatch occupancy, and service aggregates (p50/p95 latency, mean
  occupancy, cache hit rate) via :meth:`FleetService.stats`.

The service is synchronous and single-threaded by design: requests
are admitted from one host loop (a trace replay, the grader, a bench
driver) and time-based flushes happen cooperatively inside
``submit``/``pump`` — there is no background thread to race the JAX
runtime.  ``drain()`` (or exiting the context manager) flushes
everything outstanding.

Failure model (PR 5, docs/SERVING.md "Failure model"): dispatching is
ATOMIC — every request popped for a dispatch reaches a terminal state
(completed, degraded to a solo run, or failed with a typed error on
its handle) before the dispatch returns; nothing is ever re-queued
into limbo.  The machinery is service/resilience.py (bounded retry
with seeded exponential backoff, per-request deadlines, a per-bucket
circuit breaker that quarantines repeat offenders onto the solo
fallback, queue-depth admission control with typed shedding) plus
graceful mesh degradation: a device loss shrinks the lane mesh
(parallel/fleet_mesh.py ``shrink_mesh``) and rebuilds the bucket's
programs through the mesh-keyed caches.  All of it is exercised
deterministically by the seeded fault plane in service/faults.py.

Traffic/SLO plane (PR 7, docs/SERVING.md "Open-loop traffic & SLOs"):
the scheduler serves OPEN-loop request streams (service/traffic.py —
seeded Poisson/burst/diurnal arrivals, every arrival a pure function
of ``(seed, index)``) with SLO-aware scheduling (service/slo.py):
priority classes supply per-class default deadlines, ``pump()``
flushes a partial bucket EARLY when its tightest deadline minus the
bucket's estimated dispatch wall (a per-bucket EWMA of the PR-6 wall
decomposition, seeded by ``warm()``) says the batch must go now, and
per-tenant admission quotas (``tenant_quota``) layer on
``max_queue_depth`` so one hot tenant sheds typed instead of starving
the rest.  ``stats()`` splits latency windows per priority class and
``pump_harvest=False`` pins the idle in-flight harvest off for
deterministic virtual-clock traffic replays.

Elastic plane (PR 8, docs/SERVING.md "Elastic capacity"): the ladder
churns BOTH ways.  ``checkpoint_every=`` serves long dispatches as
RESUMABLE LEGS — each leg ends at a PR-1 segment cut
(models/segments.cut_for_budget), the fleet carry is snapshotted to
host numpy (core/fleet.py ``launch_leg``/``LaneCheckpoint``), and the
batch re-queues under a resume sub-bucket — so any failure retries
from the last checkpoint, never tick 0 (even the solo fallback
resumes, ``solo_resume``).  A fault-plane "device_return" event grows
the mesh back (``grow_mesh``); ``ProgramCache.rebind_mesh`` RE-KEYS
instead of evicting, so a shrink -> grow cycle finds the restored
mesh's programs warm, and queued + checkpointed lanes MIGRATE across
every rebuild (the snapshots are mesh-independent).  SLO classes now
also shape dispatch ORDER: ``pump()`` pops
tightest-queued-deadline-first (``SLOPolicy.class_ordering``).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..config import SimConfig
from ..core.fleet import FleetLeg
from ..core.tick import run_build_count
from ..models.segments import cut_for_budget
from .bucket import bucket_key, pad_configs
from .cache import ProgramCache
from .faults import FaultInjector, InjectedCompileFailure, \
    InjectedDeviceLoss, InjectedDispatchFailure
from .resilience import (BreakerPolicy, BucketQuarantined, CircuitBreaker,
                         DeadlineExceeded, DispatchFailed,
                         PoisonedLaneError, RetryPolicy, ShedRejection,
                         TenantQuotaExceeded, solo_resume, solo_run,
                         validate_checkpoint, validate_lane)
from .slo import SLOPolicy
from .types import MODES, RequestHandle, RequestMetrics, SimRequest

#: padding policies: "full" pads every dispatch to ``max_batch`` (one
#: compiled width — and so at most one build — per bucket); "pow2"
#: pads to the next power of two (less filler work, up to
#: log2(max_batch)+1 widths per bucket); "none" never pads (a width
#: per distinct batch size).
PAD_POLICIES = ("full", "pow2", "none")


@dataclass
class _Inflight:
    """One launched-but-unresolved dispatch (one slot of a bucket's
    in-flight ring): the device program is running; the host is free
    to pack the next bucket.  Resolution (block + fetch + validate +
    complete the handles) happens when a later dispatch displaces this
    slot from a full ring, or at the end of a ``flush``/``drain`` — a
    deterministic schedule, so chaos replays stay a pure function of
    submit order."""

    key: tuple
    reqs: list = field(repr=False)
    pending: object = field(repr=False)   # core.fleet.PendingFleet
    width: int
    idx: int                              # fault-plane attempt index
    fault: Optional[str]
    builds: int                           # whole-run builds at launch
    t_q0: float


class FleetService:
    """Continuous-batching scheduler over :class:`FleetSimulation`.

    >>> svc = FleetService(max_batch=8)
    >>> handles = [svc.submit(cfg, seed=s) for s in range(20)]
    >>> svc.drain()
    >>> results = [h.result() for h in handles]   # SimResult per request

    ``max_wait_s`` bounds queueing latency under trickle traffic; it
    is enforced cooperatively (checked on every ``submit``/``pump``
    against ``clock()``), not by a background thread.

    ``mesh`` (a 1-D lane mesh, ``parallel.fleet_mesh.make_lane_mesh``,
    or a 2-D lanes x peers mesh, ``make_lane_peer_mesh`` — PR 19)
    serves every dispatch from the whole mesh: ``max_batch`` becomes
    the PER-LANE-DEVICE width and the dispatch :attr:`capacity` is
    ``max_batch x n_lanes``; pad widths are rounded up to a
    lane-divisible count (every pad policy, so a partial batch always
    divides over the lane axis), and the program cache keys gain the
    mesh descriptor — now carrying the 2-D shape — so a device-count
    OR decomposition change can never be served a stale program.  On
    a 2-D mesh each simulation's peer tables additionally shard over
    the ``n_peers`` peer devices whenever ``cfg.n`` divides by the
    peer count (peer-replicated otherwise), so one lane's n is no
    longer bounded by one device's memory (docs/SERVING.md "2-D
    capacity").
    """

    def __init__(self, max_batch: int = 8,
                 max_wait_s: Optional[float] = None,
                 pad_policy: str = "full", block_size: int = 128,
                 chunk_ticks: Optional[int] = None, clock=time.perf_counter,
                 stats_window: int = 1 << 14, mesh=None,
                 cache_max_entries: Optional[int] = 64,
                 injector: Optional[FaultInjector] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[BreakerPolicy] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 degrade_to_solo: bool = True, sleep=time.sleep,
                 pipeline: Optional[bool] = None,
                 pipeline_depth: Optional[int] = None,
                 slo: Optional[SLOPolicy] = None,
                 tenant_quota: Optional[int] = None,
                 pump_harvest: Optional[bool] = None,
                 checkpoint_every: Optional[int] = None,
                 checkpoint_every_s: Optional[float] = None,
                 canonicalize: bool = False,
                 store=None, run_dir: Optional[str] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if pad_policy not in PAD_POLICIES:
            raise ValueError(f"unknown pad_policy {pad_policy!r}; "
                             f"expected one of {PAD_POLICIES}")
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1 or None, "
                             f"got {max_queue_depth}")
        if tenant_quota is not None and tenant_quota < 1:
            raise ValueError(f"tenant_quota must be >= 1 or None, "
                             f"got {tenant_quota}")
        if checkpoint_every is not None and checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1 or None, "
                             f"got {checkpoint_every}")
        if checkpoint_every_s is not None and checkpoint_every_s <= 0:
            raise ValueError(f"checkpoint_every_s must be > 0 or None, "
                             f"got {checkpoint_every_s}")
        if checkpoint_every is not None and checkpoint_every_s is not None:
            raise ValueError("checkpoint_every (ticks) and "
                             "checkpoint_every_s (seconds) are two "
                             "spellings of one budget; set at most one")
        if canonicalize and (checkpoint_every is not None
                             or checkpoint_every_s is not None):
            from .canonical import CanonicalLegUnsupported
            raise CanonicalLegUnsupported(
                "canonicalize is incompatible with checkpointed "
                "serving: legs validate resume cuts against the EXACT "
                "segment plan, which canonical buckets quantize away "
                "(docs/SERVING.md 'Bucket canonicalization')")
        # validate the mesh shape EARLY — a typed constructor error,
        # not a trace-time failure deep in shard_map — and learn the
        # axis decomposition the service speaks everywhere below:
        # batches spread over ``n_lanes``, each simulation's peer table
        # shards (when divisible) over ``n_peers``
        if mesh is not None:
            from ..parallel.fleet_mesh import mesh_axis_sizes
            n_lanes, n_peers, _ = mesh_axis_sizes(mesh)
        else:
            n_lanes, n_peers = 1, 1
        if canonicalize and n_peers & (n_peers - 1):
            raise ValueError(
                f"canonicalize over a mesh needs a power-of-two peer "
                f"axis: the pad ladder doubles, so only pow2 "
                f"peer-shard counts have peer-divisible rungs; got "
                f"{n_peers} peers")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.pad_policy = pad_policy
        self.mesh = mesh
        #: the CURRENT rung's axis decomposition (updated by
        #: ``_degrade_mesh``/``_grow_mesh`` as the ladder moves):
        #: ``n_lanes`` batch shards x ``n_peers`` peer-table shards
        self.n_lanes = n_lanes
        self.n_peers = n_peers
        self.n_devices = int(mesh.devices.size) if mesh is not None else 1
        #: the full-strength device tuple, captured at construction —
        #: the elasticity ladder's top rung: ``grow_mesh`` re-extends
        #: a degraded mesh back toward exactly these devices (PR 8)
        self._full_devices = tuple(mesh.devices.flat) \
            if mesh is not None else None
        #: the full-strength 2-D shape + axis names (PR 19): the grow
        #: ladder's target — lanes are restored first (checkpointed
        #: lanes migrate back), then the peer axis doubles toward this
        self._full_shape = tuple(mesh.devices.shape) \
            if mesh is not None else None
        self._full_axes = tuple(mesh.axis_names) \
            if mesh is not None else None
        #: canonical pad-ladder multiple: the FULL-STRENGTH peer count,
        #: pinned at construction so elastic peer-shard shrink never
        #: moves a request's canonical bucket key mid-stream
        self._canon_peers = n_peers
        #: segment budget (ticks) above which a dispatch runs as
        #: RESUMABLE LEGS (PR 8 elastic serving): each leg ends at a
        #: PR-1 segment cut (models/segments.cut_for_budget), the
        #: fleet carry is snapshotted host-side, and the batch
        #: re-queues as resume-requests — so device loss mid-sequence
        #: loses at most one leg, never the run, and checkpointed
        #: lanes migrate across mesh rebuilds.  None (default):
        #: monolithic dispatches, the pre-PR-8 behavior.  Dense
        #: bench-mode requests are always monolithic (their program
        #: compiles the active-corner width whole-run).
        self.checkpoint_every = checkpoint_every
        #: wall-clock-triggered checkpoints (ROADMAP PR-8 follow-on):
        #: a SECONDS budget converted to a tick budget per bucket via
        #: the measured wall-seconds-per-tick EWMA (``_tick_wall``,
        #: seeded by ``warm()``, updated on every dispatch from CLOCK
        #: deltas — the injected ``clock``, so a virtual/fake clock
        #: keeps the budget a deterministic pure function of the clock
        #: program) and then snapped to a legal PR-1 segment cut by
        #: ``cut_for_budget`` exactly like the tick spelling.  A
        #: bucket with no wall measurement yet dispatches monolithic
        #: (warm() seeds the estimate, so warmed buckets never do).
        self.checkpoint_every_s = checkpoint_every_s
        #: canonical bucketing (the PR 16 tentpole,
        #: service/canonical.py): requests queue and batch under
        #: EQUIVALENCE-CLASS keys — peer counts quantized to pad-ladder
        #: rungs, phase windows to the checkpoint grid, world
        #: parameters demoted to runtime operands — so a jittered
        #: mixed stream compiles one program per CLASS instead of one
        #: per distinct config.  Modes canonicalization does not serve
        #: (overlay, bench) fall back to exact buckets per request.
        self.canonicalize = canonicalize
        self.clock = clock
        self.cache = ProgramCache(block_size=block_size,
                                  chunk_ticks=chunk_ticks, mesh=mesh,
                                  max_entries=cache_max_entries,
                                  canon_rung_multiple=self._canon_peers)
        # failure plane: the (optional) deterministic fault injector
        # and the machinery that survives it (service/resilience.py)
        self.injector = injector
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = CircuitBreaker(breaker if breaker is not None
                                      else BreakerPolicy())
        self.max_queue_depth = max_queue_depth
        self.default_deadline_s = default_deadline_s
        self.degrade_to_solo = degrade_to_solo
        self._sleep = sleep
        #: the SLO plane (service/slo.py): priority classes with
        #: per-class default deadlines, and — when
        #: ``slo.early_flush`` — deadline-aware batch formation: pump
        #: flushes a partial bucket early when its tightest deadline
        #: minus the bucket's estimated dispatch wall says it must go
        #: now to make it
        self.slo = slo
        #: per-tenant admission quota, layered on ``max_queue_depth``:
        #: a tenant already holding this many QUEUED requests sheds
        #: with the typed TenantQuotaExceeded (a ShedRejection) —
        #: queued work is never dropped, and one hot tenant cannot
        #: starve the rest of the queue
        self.tenant_quota = tenant_quota
        #: the idle in-flight harvest in ``pump()`` polls real device
        #: readiness — wall-time-dependent by nature.  None (default):
        #: enabled exactly when no injector is active (the PR-6
        #: behavior); False pins it off for deterministic virtual-clock
        #: traffic runs (service/traffic.py) even without an injector
        self.pump_harvest = pump_harvest
        #: pipelined dispatch (the PR 6 tentpole, default ON;
        #: generalized to per-bucket rings by PR 17): a dispatch
        #: STAGES its batch, waits for the oldest in-flight batch in
        #: its ring ONLY when the ring is full, dispatches its own
        #: program, and only then fetches + completes the displaced
        #: batch — so staging overlaps earlier executions, fetching
        #: overlaps the next.  ``False`` is the
        #: synchronous beat (launch + resolve inside each dispatch) —
        #: kept because its un-overlapped timing is the clean
        #: device-wait-fraction measurement (under overlap the host
        #: columns are measured at their contended values even though
        #: they are hidden), and for the pipelined-vs-sync sweep
        #: (scripts/service_smoke.py pipeline; docs/PERF.md §11 has
        #: the measured steady-state comparison).
        self.pipeline = True if pipeline is None else bool(pipeline)
        if pipeline_depth is not None and int(pipeline_depth) < 1:
            raise ValueError(f"pipeline_depth must be >= 1 or None, "
                             f"got {pipeline_depth}")
        #: in-flight ring depth (PR 17): how many launched-but-
        #: unresolved batches each BUCKET may hold.  At depth 1 every
        #: bucket shares ONE service-wide slot — bit-compatible with
        #: the PR 6 beat (stage, wait previous, start, resolve
        #: previous), so depth-1 replays are digest-identical to the
        #: single-slot scheduler.  At depth >= 2 each bucket owns its
        #: own ring: independent buckets overlap on the device instead
        #: of serializing through one beat, and a bucket's own
        #: dispatches stack ``pipeline_depth`` deep before the oldest
        #: is waited on — hiding the residual per-dispatch host work
        #: behind that many executions (docs/PERF.md §11).
        self.pipeline_depth = 2 if pipeline_depth is None \
            else int(pipeline_depth)
        #: the in-flight rings: ring key -> FIFO deque of _Inflight
        #: (oldest launched first).  Ring key is ``()`` (one shared
        #: ring) at depth 1, the queue/bucket key at depth >= 2.
        #: Iteration order (ring creation order, FIFO within a ring)
        #: is the deterministic harvest order — a pure function of the
        #: submit/flush sequence, never of wall time.
        self._rings: dict[tuple, deque] = {}
        #: dispatches that found their ring FULL and had to displace
        #: (wait on) the oldest in-flight batch before starting — the
        #: pipeline back-pressure counter surfaced by stats()
        self._ring_stalls = 0
        self._has_deadlines = False   # gates the per-pump queue scan
        self._attempts = 0      # dispatch-attempt counter = the fault
        #                         schedule's index (service/faults.py)
        self._queues: dict[tuple, deque] = {}
        self._handles: dict[int, RequestHandle] = {}
        self._filler: dict[tuple, SimConfig] = {}
        self._next_rid = 0
        self._completed = 0
        self._failed = 0
        # service aggregates over a bounded sliding window: a
        # long-lived stream must not grow host memory per request, so
        # stats() percentiles/means describe the last ``stats_window``
        # latencies and dispatches (counters stay lifetime-exact)
        self._latencies: deque = deque(maxlen=stats_window)
        self._dispatches: deque = deque(maxlen=max(1, stats_window // 8))
        self._dispatch_count = 0
        self._bucket_stats: dict[tuple, dict] = {}
        # per-priority-class observability (the open-loop plane): one
        # bounded latency window PER class — a single global window
        # mixes classes and epochs under sustained mixed traffic, so
        # per-class p50/p99 would be meaningless — plus lifetime
        # per-class terminal counters; the aggregate fields above are
        # unchanged
        self._stats_window = stats_window
        self._class_lat: dict[str, deque] = {}
        self._class_stats: dict[str, dict] = {}
        self._tenant_shed: dict[str, int] = {}
        # queued-request count per tenant, maintained at every queue
        # mutation (enqueue / pop / requeue / expiry) so quota
        # admission is O(1) instead of a full queue scan per submit
        self._tenant_queued: dict[str, int] = {}
        self._early_flushes = 0
        # WFQ service counters (slo.weights): lanes dispatched per
        # class, the deficit the pump order normalizes by weight
        self._wfq_served: dict[str, float] = {}
        # per-bucket dispatch-wall EWMA (seconds), seeded by warm():
        # the early-flush estimate (PR 6's wall decomposition already
        # measures the wall; this just remembers it per bucket)
        self._bucket_wall: dict[tuple, float] = {}
        # per-BASE-bucket wall-seconds-per-TICK EWMA, from clock()
        # deltas around each dispatch (so a virtual clock keeps it
        # deterministic); the checkpoint_every_s -> tick-budget
        # conversion reads it
        self._tick_wall: dict[tuple, float] = {}
        # failure-domain counters (lifetime-exact, like the request/
        # dispatch counters; the windowed view rides the _dispatches
        # entries' "retries" field)
        self._failures = {
            "retries": 0, "backoff_s": 0.0, "deadline_misses": 0,
            "shed": 0, "breaker_opens": 0, "degraded_dispatches": 0,
            "degraded_requests": 0, "failed_requests": 0,
            "device_losses": 0, "device_returns": 0,
            "mesh_rebuilds": 0,
            "faults_injected": 0, "poisoned_lanes": 0,
            "injected_latency_s": 0.0,
        }
        # the elasticity counters (PR 8): lifetime-exact, reported in
        # stats()["elastic"] so a grow seed's replay can be compared
        # event-for-event.  restarted_lanes counts checkpointed work
        # ever re-run from tick 0 — structurally 0 (retries resume
        # from the last checkpoint; even the solo fallback resumes)
        # and gated on 0 by the elastic replay harness.
        self._elastic = {
            "mesh_grows": 0, "checkpoints_taken": 0,
            "lanes_migrated": 0, "resume_dispatches": 0,
            "restarted_lanes": 0,
        }
        #: the durability plane (PR 12, gossip_protocol_tpu/store/):
        #: a RunStore (or ``run_dir`` sugar for one) makes this
        #: service journal every decision and write every checkpoint
        #: cut through the content-addressed spill tier — queued
        #: requests then hold lightweight SpilledCheckpoint proxies
        #: instead of full snapshots, and ``FleetService.recover``
        #: can rebuild the run in a fresh process.  None (default):
        #: the pre-PR-12 in-RAM-only behavior, bit for bit.
        if run_dir is not None and store is None:
            from ..store import RunStore
            store = RunStore(run_dir)
        self.store = store
        if store is not None:
            store.journal.meta({
                "max_batch": max_batch, "pad_policy": pad_policy,
                "pipeline": self.pipeline,
                "pipeline_depth": self.pipeline_depth,
                "checkpoint_every": checkpoint_every,
                "checkpoint_every_s": checkpoint_every_s,
                "mesh_devices": self.n_devices,
                "mesh_shape": [self.n_lanes, self.n_peers],
            })

    # ---- admission ---------------------------------------------------
    def submit(self, cfg: SimConfig, seed: Optional[int] = None,
               mode: str = "trace",
               deadline_s: Optional[float] = None,
               priority: Optional[str] = None,
               tenant: Optional[str] = None) -> RequestHandle:
        """Admit one simulation request; returns immediately.

        ``seed`` is sugar for ``cfg.replace(seed=seed)``.  Admission
        also runs the cooperative flush pass, so a submit can complete
        earlier requests (its own too, when it fills a batch).

        ``priority`` names an SLO class (service/slo.py) when the
        service carries an ``slo`` policy: it is validated against the
        policy and supplies the request's default deadline; without a
        policy it is a free-form label (default ``"default"``) used
        only for per-class stats.  ``tenant`` attributes the request
        for per-tenant admission quotas (``tenant_quota``) and shed
        accounting.

        ``deadline_s`` (or, absent it, the class default when an
        ``slo`` policy rides — the policy OWNS deadlines, so a
        deadline-less class stays deadline-less — or the service's
        ``default_deadline_s`` on policy-less services) is a relative
        latency budget on the service clock: a request still queued
        past it fails fast with :class:`DeadlineExceeded`; one that
        completes late is delivered with ``metrics.deadline_missed``
        set.  When the queue already holds ``max_queue_depth``
        requests — or the tenant already holds ``tenant_quota`` queued
        requests — admission sheds with a typed
        :class:`ShedRejection` — load is never shed by silently
        dropping something already queued.
        """
        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}; expected one "
                             f"of {MODES}")
        if self.slo is not None:
            priority = self.slo.resolve(priority)
        elif priority is None:
            priority = "default"
        if self.max_queue_depth is not None \
                and self.pending >= self.max_queue_depth:
            self._failures["shed"] += 1
            raise ShedRejection(self.pending, self.max_queue_depth)
        if self.tenant_quota is not None and tenant is not None:
            held = self._tenant_queued.get(tenant, 0)
            if held >= self.tenant_quota:
                self._failures["shed"] += 1
                self._tenant_shed[tenant] = \
                    self._tenant_shed.get(tenant, 0) + 1
                raise TenantQuotaExceeded(tenant, held, self.tenant_quota)
        if seed is not None:
            cfg = cfg.replace(seed=int(seed))
        key = self._bucket(cfg, mode)
        now = self.clock()
        budget = deadline_s
        if budget is None:
            # an SLO policy OWNS the deadline decision: a class
            # declared deadline-less STAYS deadline-less — the
            # service-wide default applies only on policy-less
            # services (otherwise ClassPolicy(deadline_s=None) could
            # not express "throughput-only" at all)
            budget = self.slo.deadline_for(priority) \
                if self.slo is not None else self.default_deadline_s
        req = SimRequest(rid=self._next_rid, cfg=cfg, mode=mode,
                         bucket=key, submit_s=now,
                         deadline_s=(now + budget
                                     if budget is not None else None),
                         priority=priority, tenant=tenant)
        if req.deadline_s is not None:
            self._has_deadlines = True
        self._next_rid += 1
        handle = RequestHandle(request=req, _service=self)
        self._handles[req.rid] = handle
        self._queues.setdefault(key, deque()).append(req)
        self._tenant_note(req.tenant, +1)
        self._filler.setdefault(key, cfg)
        self._bucket_stats.setdefault(key, {"requests": 0, "dispatches": 0,
                                            "builds": 0})
        self._bucket_stats[key]["requests"] += 1
        if self.store is not None:
            self.store.journal.submit(req)
        self.pump()
        return handle

    def _readmit(self, rid: int, cfg: SimConfig, mode: str,
                 priority: str = "default",
                 tenant: Optional[str] = None,
                 resume=None) -> RequestHandle:
        """Re-admit one journaled request during crash recovery
        (store/recovery.py) under its ORIGINAL rid.

        Mirrors :meth:`submit`'s bookkeeping with three deliberate
        differences: no new journal record (the original submit
        record stands — a second recovery must not see duplicates),
        no admission control (the request was already admitted once;
        shedding it now would drop accepted work), and no ``pump()``
        (recovery queues everything first so resumed batches re-form
        at full width).  ``resume`` is the lane's latest loadable
        spilled cut (a SpilledCheckpoint proxy) — the request queues
        directly under the matching resume sub-bucket, exactly where
        the dead process left it.
        """
        key = self._bucket(cfg, mode)
        req = SimRequest(rid=rid, cfg=cfg, mode=mode, bucket=key,
                         submit_s=self.clock(), priority=priority,
                         tenant=tenant)
        if resume is not None:
            req.resume = resume
            req.bucket = key + (("resume", int(resume.tick)),)
        handle = RequestHandle(request=req, _service=self)
        self._handles[rid] = handle
        self._queues.setdefault(req.bucket, deque()).append(req)
        self._tenant_note(tenant, +1)
        self._filler.setdefault(key, cfg)
        self._bucket_stats.setdefault(key, {"requests": 0,
                                            "dispatches": 0,
                                            "builds": 0})
        self._bucket_stats[key]["requests"] += 1
        self._next_rid = max(self._next_rid, rid + 1)
        return handle

    @classmethod
    def recover(cls, run_dir: str, mesh=None, **kw):
        """Rebuild a service (and its pending work) from a dead
        process's run directory: replay the write-ahead journal,
        re-warm the program cache, re-admit every non-terminal
        request, and resume each from its last spilled cut.  Returns
        ``(service, handles)``; drive the service (``drain()`` /
        per-handle ``result()``) to finish the run.  Full semantics:
        store/recovery.py."""
        from ..store.recovery import recover_service
        return recover_service(run_dir, mesh=mesh, **kw)

    @property
    def capacity(self) -> int:
        """Lanes one dispatch can carry: ``max_batch`` per LANE
        device, times the lane axis (1 without a mesh).  On a 2-D
        mesh the peer axis does not multiply capacity — those devices
        shard each simulation's peer tables instead (n-scaling, not
        batch-scaling)."""
        return self.max_batch * self.n_lanes

    # ---- flush policies ----------------------------------------------
    def pump(self) -> int:
        """One cooperative scheduling pass; returns dispatches made.

        Flushes every bucket that is full (:attr:`capacity`), every
        bucket whose oldest request has waited past ``max_wait_s``,
        and — under an ``slo`` policy with ``early_flush`` — every
        bucket whose tightest deadline minus its estimated dispatch
        wall says a partial batch must dispatch NOW to make its SLO
        (:meth:`_should_flush_early`).  A pump that made no dispatch
        also HARVESTS finished in-flight batches (non-blocking
        ``is_ready`` check on each ring's oldest slot,
        :meth:`_harvest_ready`), so a poll-driven caller sees
        completions during idle periods without forcing a flush —
        except when
        :meth:`_harvest_enabled` says no: under an active fault
        injector (a readiness check is wall-time-dependent, and a
        fault surfacing at resolve would consume retry attempt
        indices at a timing-dependent point, breaking the chaos
        plane's digest-for-digest replayability), or when
        ``pump_harvest=False`` pins it off for deterministic
        virtual-clock traffic runs (service/traffic.py) that have no
        injector but still must not stamp completion times at
        wall-dependent points.
        """
        n = 0
        self._expire_deadlines(self.clock())
        for key in self._pump_order():
            q = self._queues[key]
            while len(q) >= self.capacity:
                self._dispatch(key)
                n += 1
            # re-read the clock per bucket: a multi-second dispatch
            # above (or for an earlier bucket) can erode another
            # bucket's deadline margin within this same pass — a
            # stale timestamp would miss exactly the flush-now window
            # the SLO check exists to catch.  (On a virtual clock the
            # re-read returns the same value: determinism unaffected.)
            now = self.clock()
            if (q and self.max_wait_s is not None
                    and now - q[0].submit_s >= self.max_wait_s):
                self._dispatch(key)
                n += 1
            if q and self._should_flush_early(key, q, now):
                self._early_flushes += 1
                self._dispatch(key)
                n += 1
        if n == 0 and self._harvest_enabled():
            self._harvest_ready()
        return n

    def _pump_order(self) -> list:
        """The bucket order one ``pump()`` pass serves.

        FIFO over bucket creation order, UNLESS an SLO policy with
        ``class_ordering`` rides (PR 8 satellite): then buckets are
        popped tightest-queued-deadline first — through PR 7 classes
        shaped deadlines but not dispatch order, so an interactive
        batch could sit a full dispatch wall behind a deadline-less
        bulk bucket that merely enqueued earlier.  Deadline-less
        buckets keep FIFO order after every deadline-carrying one.
        With ``slo.weights`` set (PR 9 satellite), WEIGHTED FAIR
        QUEUING replaces tightest-first: buckets order by their
        dominant class's normalized service deficit (lanes dispatched
        so far / weight, least-served first), so a heavy class earns
        a proportional share of dispatch slots without starving light
        ones.  Deterministic either way: deadlines/weights are pure
        schedule values on a virtual clock and ties break on creation
        order, so digest replays are unaffected
        (tests/test_traffic.py).
        """
        keys = list(self._queues)
        if self.slo is None \
                or not getattr(self.slo, "class_ordering", True):
            return keys
        pos = {k: i for i, k in enumerate(keys)}
        if getattr(self.slo, "weights", None) is not None:
            def deficit(k):
                cls = self._dominant_class(self._queues[k])
                served = self._wfq_served.get(cls, 0.0)
                return (served / self.slo.weight_of(cls), pos[k])
            keys.sort(key=deficit)
            return keys

        def tightness(k):
            dls = [r.deadline_s for r in self._queues[k]
                   if r.deadline_s is not None]
            return (min(dls) if dls else float("inf"), pos[k])

        keys.sort(key=tightness)
        return keys

    def _dominant_class(self, q) -> str:
        """The WFQ class a bucket is charged to: the priority class
        holding the most queued requests (ties break on class name —
        deterministic)."""
        counts: dict[str, int] = {}
        for r in q:
            counts[r.priority] = counts.get(r.priority, 0) + 1
        if not counts:
            return self.slo.default_class if self.slo is not None \
                else "default"
        return max(sorted(counts), key=lambda c: counts[c])

    def _harvest_enabled(self) -> bool:
        """Whether an idle ``pump()`` may resolve a ready in-flight
        batch.  Explicit ``pump_harvest`` wins; the default enables it
        exactly when no fault injector is active."""
        if self.pump_harvest is not None:
            return bool(self.pump_harvest)
        return self.injector is None

    def _should_flush_early(self, key: tuple, q, now: float) -> bool:
        """Deadline-aware batch formation (service/slo.py): True when
        the bucket's tightest queued deadline leaves no more margin
        than the estimated dispatch wall (times the policy's safety
        factor, plus its fixed margin).  Requests whose deadline
        already passed were expired by ``_expire_deadlines`` before
        this runs, so the margin here is positive."""
        if self.slo is None or not self.slo.early_flush:
            return False
        rem = self._min_remaining(q, now)
        if rem is None:
            return False
        est = self._est_wall(key)
        return rem <= est * self.slo.safety_factor + self.slo.margin_s

    def _est_wall(self, key: tuple) -> float:
        """Estimated dispatch wall for one bucket: the pinned value
        when the SLO policy carries one (deterministic replays), else
        the bucket's measured EWMA (seeded by ``warm()``), else the
        mean over buckets that HAVE dispatched, else 0 (flush only on
        the fixed margin until the first wall is measured)."""
        if self.slo is not None \
                and self.slo.assumed_dispatch_wall_s is not None:
            return self.slo.assumed_dispatch_wall_s
        if key in self._bucket_wall:
            return self._bucket_wall[key]
        if self._bucket_wall:
            return sum(self._bucket_wall.values()) / len(self._bucket_wall)
        return 0.0

    def flush(self, bucket: Optional[tuple] = None) -> int:
        """Dispatch everything pending (in one bucket, or all), then
        resolve any in-flight batch: after ``flush()`` returns, every
        request that was queued or in flight has reached a terminal
        handle state (the post-PR-6 flush guarantee; under pipelining
        a ``pump()`` alone may leave the newest batch in flight) — OR,
        under checkpointed serving (PR 8), has been advanced one leg
        and re-queued under its next resume sub-bucket.  A whole-
        service flush loops until every queue is empty AND nothing is
        in flight, so its terminal guarantee covers legs too (each
        pass advances every leg at least one cut — the loop is
        finite); a single-bucket flush drains that bucket once
        (``RequestHandle.result`` re-flushes the request's CURRENT
        bucket as it moves)."""
        n = 0
        self._expire_deadlines(self.clock())
        if bucket is not None:
            while self._queues.get(bucket):
                self._dispatch(bucket)
                n += 1
            self.resolve_inflight()
            return n
        while True:
            keys = [k for k in self._queues if self._queues[k]]
            if not keys and not any(self._rings.values()):
                break
            for key in keys:
                while self._queues.get(key):
                    self._dispatch(key)
                    n += 1
            # resolving may CHECKPOINT the in-flight batch and
            # re-queue it one leg further — loop back around
            self.resolve_inflight()
        return n

    def drain(self) -> int:
        """Flush all buckets; the stream is over (for now)."""
        return self.flush()

    @property
    def pending(self) -> int:
        """Requests still queued (in-flight requests are counted by
        :attr:`in_flight`, not here)."""
        return sum(len(q) for q in self._queues.values())

    @property
    def in_flight(self) -> int:
        """Requests launched on device but not yet resolved (summed
        over every bucket's in-flight ring)."""
        return sum(len(i.reqs) for i in self._inflight_batches())

    def _ring_key(self, key: tuple) -> tuple:
        """The ring a dispatch's in-flight slot lives in: one shared
        ring (``()``) at depth 1 — exactly the PR 6 service-wide slot,
        so any bucket's dispatch displaces any other's — the dispatch's
        own queue key at depth >= 2, so only same-bucket dispatches
        queue behind each other and independent buckets overlap."""
        return () if self.pipeline_depth == 1 else key

    def _inflight_batches(self) -> list:
        """Every in-flight batch, in the deterministic harvest order:
        ring creation order, oldest-launched first within a ring — a
        pure function of the submit/flush sequence (no wall clock, no
        readiness probe), which is what keeps chaos/elastic digest
        replays depth-stable."""
        return [i for ring in self._rings.values() for i in ring]

    def _pop_oldest_inflight(self) -> Optional[_Inflight]:
        """Detach the next in-flight batch in harvest order (pruning
        emptied rings); None when nothing is in flight."""
        for rkey in list(self._rings):
            ring = self._rings[rkey]
            if ring:
                infl = ring.popleft()
                if not ring:
                    del self._rings[rkey]
                return infl
            del self._rings[rkey]
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    # ---- dispatch ----------------------------------------------------
    def _bucket(self, cfg: SimConfig, mode: str) -> tuple:
        """The queue/bucket key for one request: the equivalence-class
        key when ``canonicalize`` is on (service/canonical.py;
        requests it cannot serve fall back to exact keys inside
        ``canonical_bucket_key``), the exact ``bucket_key``
        otherwise."""
        if self.canonicalize:
            from .canonical import canonical_bucket_key
            # the FULL-STRENGTH peer count snaps the pad ladder to
            # peer-shard-divisible rungs; pinned at construction so an
            # elastic peer-shard shrink never moves a bucket key
            return canonical_bucket_key(cfg, mode,
                                        peers=self._canon_peers)
        return bucket_key(cfg, mode)

    @staticmethod
    def _base_key(key: tuple) -> tuple:
        """A queue key without its resume marker (PR 8): checkpointed
        batches queue under ``base + (("resume", tick),)`` — lanes at
        different clocks must never share a dispatch (a fleet shares
        ONE scan clock) — but the program cache, circuit breaker, and
        per-bucket stats all speak the BASE bucket."""
        if key and isinstance(key[-1], tuple) and key[-1] \
                and key[-1][0] == "resume":
            return key[:-1]
        return key

    def _leg_ticks(self, reqs: list) -> Optional[int]:
        """Leg length for this batch (None: monolithic dispatch).

        A batch runs as resumable legs when ``checkpoint_every`` is
        set, the engine supports the mode (every overlay request;
        dense ``trace``), and the config's segment plan offers an
        interior cut — each leg ends at the cut
        ``models/segments.cut_for_budget`` picks.  Resumed batches
        ALWAYS take the leg path (their carry lives in checkpoints).
        All lanes of a batch share the plan (the bucket pins the plan
        signature, and cuts are seed-independent), so one leg length
        serves the whole batch."""
        r0 = reqs[0]
        cfg = r0.cfg
        budget = self.checkpoint_every
        if budget is None and self.checkpoint_every_s is not None:
            budget = self._ticks_for_seconds(self._base_key(r0.bucket))
        if budget is None:
            if r0.resume is not None:
                # a resumed batch must take the leg path (its carry
                # lives in checkpoints) even if the seconds budget has
                # no estimate yet: run it to the end in one leg
                return cfg.total_ticks - r0.resume.tick
            return None
        if cfg.model != "overlay" and r0.mode != "trace":
            return None     # dense bench: monolithic by construction
        start = r0.resume.tick if r0.resume is not None else 0
        end = cut_for_budget(cfg, start, budget)
        if r0.resume is None and end >= cfg.total_ticks:
            return None     # no interior cut (or the run fits the
            #                 budget): nothing to checkpoint
        return end - start

    def _ticks_for_seconds(self, base: tuple) -> Optional[int]:
        """The seconds budget as ticks, via the bucket's measured
        wall-per-tick EWMA (falling back to the cross-bucket mean);
        None until any estimate exists — an unwarmed bucket's first
        dispatch runs monolithic rather than guessing."""
        spt = self._tick_wall.get(base)
        if spt is None and self._tick_wall:
            spt = sum(self._tick_wall.values()) / len(self._tick_wall)
        if spt is None or spt <= 0.0:
            return None
        return max(1, int(self.checkpoint_every_s / spt))

    def _note_tick_wall(self, base: tuple, wall_s: float,
                        ticks: int) -> None:
        if ticks <= 0 or wall_s < 0.0:
            return
        alpha = self.slo.wall_ewma_alpha if self.slo is not None else 0.3
        prev = self._tick_wall.get(base)
        spt = wall_s / ticks
        self._tick_wall[base] = spt if prev is None \
            else (1.0 - alpha) * prev + alpha * spt

    def _width(self, k: int) -> int:
        """Compiled lane width for a ``k``-request batch.

        Every policy's width is rounded UP to a multiple of the LANE
        axis (a lane-sharded fleet needs ``B % n_lanes == 0``; without
        a mesh this is a no-op — and the peer axis never constrains
        the batch width, it shards within each lane), and under a mesh
        the "full" width is the whole-mesh :attr:`capacity` — one
        compiled width, and so at most one build, per bucket either
        way.
        """
        if self.pad_policy == "none":
            w = k
        elif self.pad_policy == "pow2":
            w = min(self.capacity, 1 << (k - 1).bit_length())
        else:
            w = self.capacity
        # a mesh shrink mid-flight can leave an already-popped batch
        # wider than the NEW capacity; the width must still cover it
        w = max(w, k)
        d = self.n_lanes
        return -(-w // d) * d

    def _dispatch(self, key: tuple) -> None:
        """Pop one batch and serve it.  Synchronous mode resolves it
        ATOMICALLY before returning (the PR-5 contract); pipelined
        mode may leave the batch IN FLIGHT (a slot in its bucket's
        ring, ``self._rings``), to be resolved when a later dispatch
        displaces it from a full ring, an idle pump harvests it, or
        the flush ends — either way every popped request reaches a
        terminal state by the time ``flush()``/``drain()`` returns.
        Only non-Exception escapes (KeyboardInterrupt, SystemExit)
        re-queue still-unresolved requests at the queue front and
        propagate."""
        q = self._queues[key]
        reqs = [q.popleft() for _ in range(min(len(q), self.capacity))]
        for r in reqs:
            self._tenant_note(r.tenant, -1)
            self._wfq_served[r.priority] = \
                self._wfq_served.get(r.priority, 0.0) + 1.0
        try:
            if self.pipeline:
                self._serve_batch_pipelined(key, reqs)
            else:
                self._serve_batch(key, reqs)
        except BaseException:
            # backstop requeue, DEDUPED: the pipelined path's inner
            # handlers may already have requeued these requests (and
            # aborted the in-flight rings) before re-raising — a
            # request is put back only if it is still unresolved AND
            # not already waiting in the queue or riding in flight,
            # so an interrupted flush can be flushed again without
            # duplicate queue entries
            keep = {r.rid for i in self._inflight_batches()
                    for r in i.reqs}
            queued = {r.rid for r in q}
            unresolved = [r for r in reqs if r.rid in self._handles
                          and r.rid not in keep and r.rid not in queued]
            q.extendleft(reversed(unresolved))
            for r in unresolved:
                self._tenant_note(r.tenant, +1)
            self._abort_inflight()
            # requeues may have landed from several points (a failing
            # resolve, the abort above, this backstop); restore submit
            # order so the next flush serves oldest-first — normal
            # queue order IS rid order, so the sort is idempotent
            for qq in self._queues.values():
                if len(qq) > 1:
                    ordered = sorted(qq, key=lambda r: r.rid)
                    qq.clear()
                    qq.extend(ordered)
            raise

    def _requeue_unresolved(self, key: tuple, reqs: list) -> None:
        """Interrupted-dispatch recovery: put still-unresolved
        requests back at the front of their queue (submit order kept)."""
        q = self._queues.setdefault(key, deque())
        back = [r for r in reqs if r.rid in self._handles]
        for r in back:
            self._handles[r.rid]._launched = False
            self._tenant_note(r.tenant, +1)
        q.extendleft(reversed(back))

    def _abort_inflight(self) -> None:
        """Re-queue every in-flight batch, all rings (non-Exception
        escape path)."""
        while True:
            infl = self._pop_oldest_inflight()
            if infl is None:
                return
            self._requeue_unresolved(infl.key, infl.reqs)

    def resolve_inflight(self) -> None:
        """Resolve every in-flight batch, all rings, in the
        deterministic harvest order: block until each program
        completes, fetch + validate, and terminally resolve its
        handles (retrying / degrading on failure exactly like a
        synchronous dispatch).  Each batch is detached from its ring
        BEFORE resolving, so a non-Exception escape mid-resolve leaves
        the not-yet-resolved batches still registered in flight."""
        while True:
            infl = self._pop_oldest_inflight()
            if infl is None:
                return
            self._resolve(infl)

    def _harvest_ready(self) -> int:
        """The idle-pump harvest, generalized to the rings: resolve
        every ring HEAD whose program reports ready (non-blocking
        ``PendingFleet.is_ready``), repeating until no head is ready —
        only a ring's oldest slot may be harvested, so within-bucket
        resolution order stays FIFO even though readiness is polled.
        Returns batches resolved.  Wall-dependent by nature (the
        readiness probe), which is why ``_harvest_enabled`` gates it
        off under a fault injector or ``pump_harvest=False``."""
        done = 0
        progressed = True
        while progressed:
            progressed = False
            for rkey in list(self._rings):
                ring = self._rings.get(rkey)
                if ring and ring[0].pending.is_ready():
                    infl = ring.popleft()
                    if not ring:
                        self._rings.pop(rkey, None)
                    self._resolve(infl)
                    done += 1
                    progressed = True
        return done

    # ---- resilient dispatch (service/resilience.py) ------------------
    def _serve_batch(self, key: tuple, reqs: list) -> None:
        """Synchronous dispatch: one attempt (launch + resolve), then
        the shared recovery path on failure."""
        now = self.clock()
        reqs = self._drop_expired(reqs, now)
        if not reqs:
            return
        t_q0 = now              # queue wait ends at the first attempt
        if not self.breaker.allow(self._base_key(key), now):
            # quarantined bucket: straight to the ladder's bottom rung
            self._degrade_batch(key, reqs, t_q0, retries=0)
            return
        err, idx = self._try_once(key, reqs, t_q0, retries=0)
        if err is not None:
            self._recover_batch(key, reqs, t_q0, attempt=1,
                                last_err=err, last_idx=idx)

    def _serve_batch_pipelined(self, key: tuple, reqs: list) -> None:
        """Pipelined dispatch through the bucket's in-flight ring:
        STAGE this batch's lanes (host packing + the tiny device
        staging programs) while earlier programs execute, then — only
        if the ring is FULL — wait for and displace the ring's oldest
        batch, then dispatch this batch's program, then resolve the
        displaced batch while this one executes.  Staging is the host
        work that used to serialize with execution — overlapping it is
        what breaks the host-bound serving ceiling (docs/PERF.md §11).

        At depth 1 the ring is one service-wide slot, so every
        dispatch displaces: the beat is exactly PR 6's stage -> wait
        previous -> start -> resolve previous, and no two fleet
        programs ever compute concurrently (on XLA:CPU concurrent
        programs share the cores and fetches queue behind the new
        program — measured slower than no pipelining at all).  At
        depth >= 2 a dispatch into a ring with a free slot starts
        IMMEDIATELY: independent buckets overlap on the device, and a
        bucket's own dispatches stack ``pipeline_depth`` deep before
        the oldest is waited on — the concurrency is the point on
        hardware where host and device do not share silicon."""
        now = self.clock()
        reqs = self._drop_expired(reqs, now)
        if not reqs:
            return
        t_q0 = now
        if not self.breaker.allow(self._base_key(key), now):
            # resolve the in-flight batch first: the quarantined
            # bucket's solo runs (and their sleeps) must not strand
            # it, nor contend with its still-executing program
            self.resolve_inflight()
            self._degrade_batch(key, reqs, t_q0, retries=0)
            return
        idx, fault = self._draw_attempt()
        builds0 = run_build_count()
        try:
            pending, width = self._attempt_launch(key, reqs, fault, idx,
                                                  defer=True)
        except Exception as e:
            # staging failed before any overlap existed; resolve the
            # independent in-flight batch FIRST so the retry/degrade
            # path below (backoff sleeps, solo runs) cannot strand it
            self.resolve_inflight()
            try:
                self._recover_batch(key, reqs, t_q0, attempt=1,
                                    last_err=e, last_idx=idx)
            except BaseException:
                self._requeue_unresolved(key, reqs)
                raise
            return
        builds = run_build_count() - builds0
        if pending.started:
            # the engine could not defer this launch (multi-chunk
            # dense traces execute eagerly inside launch()) — there is
            # no overlap to orchestrate, so fall back to the
            # synchronous beat: previous batch first, then this one,
            # never two programs pretending to pipeline
            self.resolve_inflight()
            infl = _Inflight(key=key, reqs=reqs, pending=pending,
                             width=width, idx=idx, fault=fault,
                             builds=builds, t_q0=t_q0)
            try:
                fleet = self._finish_attempt(infl)
            except Exception as e:
                try:
                    self._recover_batch(key, reqs, t_q0, attempt=1,
                                        last_err=e, last_idx=idx)
                except BaseException:
                    self._requeue_unresolved(key, reqs)
                    raise
                return
            except BaseException:
                self._requeue_unresolved(key, reqs)
                raise
            self.breaker.record_success(self._base_key(key))
            self._complete_batch(key, reqs, fleet, width, builds, t_q0,
                                 retries=0)
            return
        for r in reqs:
            self._handles[r.rid]._launched = True
        infl = _Inflight(key=key, reqs=reqs, pending=pending,
                         width=width, idx=idx, fault=fault,
                         builds=builds, t_q0=t_q0)
        rkey = self._ring_key(key)
        ring = self._rings.setdefault(rkey, deque())
        # the ring beat, in order: (1) if this batch's ring is full,
        # wait for its OLDEST batch's program to finish WITHOUT
        # fetching (a ring stall — the only point the pipeline ever
        # blocks on the device), (2) dispatch this batch's program,
        # (3) fetch + complete the displaced batch while this one
        # executes.  A ring with a free slot skips (1) and (3)
        # entirely: the program starts with zero waiting and
        # resolution is deferred to a later displacement, harvest, or
        # flush.
        prev: Optional[_Inflight] = None
        if len(ring) >= self.pipeline_depth:
            prev = ring.popleft()
            self._ring_stalls += 1
        ring.append(infl)
        if prev is not None:
            try:
                prev.pending.wait()
            except Exception:
                pass             # surfaces again inside _resolve below
            except BaseException:
                self._requeue_unresolved(prev.key, prev.reqs)
                self._abort_inflight()
                raise
        start_err: Optional[Exception] = None
        try:
            pending.start()
        except Exception as e:
            ring.pop()           # infl is the newest slot
            if not ring:
                self._rings.pop(rkey, None)
            start_err = e
        except BaseException:
            if prev is not None:
                self._requeue_unresolved(prev.key, prev.reqs)
            self._abort_inflight()
            raise
        if prev is not None:
            self._resolve(prev)
        if start_err is not None:
            try:
                self._recover_batch(key, reqs, t_q0, attempt=1,
                                    last_err=start_err, last_idx=idx)
            except BaseException:
                self._requeue_unresolved(key, reqs)
                raise

    def _resolve(self, infl: _Inflight) -> None:
        """Finish one launched dispatch: block + fetch + validate +
        complete the handles; failures re-enter the shared recovery
        path (synchronous retries — the batch is no longer pipelined)."""
        try:
            fleet = self._finish_attempt(infl)
        except Exception as e:
            try:
                self._recover_batch(infl.key, infl.reqs, infl.t_q0,
                                    attempt=1, last_err=e,
                                    last_idx=infl.idx)
            except BaseException:
                self._requeue_unresolved(infl.key, infl.reqs)
                raise
            return
        except BaseException:
            self._requeue_unresolved(infl.key, infl.reqs)
            raise
        self.breaker.record_success(self._base_key(infl.key))
        self._complete_batch(infl.key, infl.reqs, fleet, infl.width,
                             infl.builds, infl.t_q0, retries=0)

    def _draw_attempt(self):
        """Allocate the next dispatch-attempt index and consult the
        fault plane for it — the ONE place this happens: the chaos
        schedule's determinism depends on pipelined first attempts and
        synchronous retries drawing from the identical sequence."""
        self._attempts += 1
        idx = self._attempts
        fault = (self.injector.plan(idx)
                 if self.injector is not None else None)
        if fault is not None:
            self._failures["faults_injected"] += 1
            if self.store is not None:
                self.store.journal.fault(idx, fault)
        return idx, fault

    def _try_once(self, key: tuple, reqs: list, t_q0: float,
                  retries: int):
        """One full synchronous attempt (launch + immediate resolve);
        returns ``(None, idx)`` on success or ``(error, idx)``."""
        idx, fault = self._draw_attempt()
        builds0 = run_build_count()
        try:
            pending, width = self._attempt_launch(key, reqs, fault, idx)
            builds = run_build_count() - builds0
            fleet = self._finish_attempt(_Inflight(
                key=key, reqs=reqs, pending=pending, width=width,
                idx=idx, fault=fault, builds=builds, t_q0=t_q0))
        except Exception as e:
            return e, idx
        self.breaker.record_success(self._base_key(key))
        self._complete_batch(key, reqs, fleet, width, builds, t_q0,
                             retries=retries)
        return None, idx

    def _recover_batch(self, key: tuple, reqs: list, t_q0: float,
                       attempt: int, last_err: BaseException,
                       last_idx: int) -> None:
        """The shared failure path: record the failure that brought us
        here, then bounded synchronous retries with seeded backoff;
        exhaustion degrades to the solo fallback.  ``attempt`` counts
        failed attempts so far (>= 1)."""
        while True:
            if isinstance(last_err, InjectedDeviceLoss):
                self._failures["device_losses"] += 1
                if self.mesh is not None:
                    self._degrade_mesh()
            if self.breaker.record_failure(self._base_key(key), self.clock()):
                self._failures["breaker_opens"] += 1
            now = self.clock()
            reqs = self._drop_expired(reqs, now)
            if not reqs:
                return
            backoff = self.retry.backoff_s(attempt, salt=last_idx)
            remaining = self._min_remaining(reqs, now)
            if attempt > self.retry.max_retries or \
                    (remaining is not None and backoff >= remaining):
                break
            self._failures["retries"] += 1
            self._failures["backoff_s"] += backoff
            self._sleep(backoff)
            err, last_idx = self._try_once(key, reqs, t_q0,
                                           retries=attempt)
            if err is None:
                return
            last_err = err
            attempt += 1
        # retries exhausted: degrade to the solo fallback (or fail
        # terminally when the fallback is disabled)
        self._degrade_batch(key, reqs, t_q0, retries=attempt,
                            last_err=last_err)

    def _attempt_launch(self, key: tuple, reqs: list,
                        fault: Optional[str], idx: int,
                        defer: bool = False):
        """The launch half of a dispatch attempt, with the fault plane
        consulted at each pre-execution boundary; returns
        ``(PendingFleet, width)`` or raises.  The program is dispatched
        asynchronously — compute continues while this returns; with
        ``defer=True`` it is only STAGED (``PendingFleet.start()``
        dispatches), which is how the pipelined path keeps the next
        program off the cores until the previous batch resolves."""
        if fault == "device_return":
            # the elastic fault event (PR 8): a lost device came back.
            # Not a failure — grow the mesh BEFORE this launch so the
            # batch (and every checkpointed lane it carries) lands on
            # the wider mesh, then proceed normally.
            self._failures["device_returns"] += 1
            self._grow_mesh()
            fault = None
        if fault == "device_loss":
            raise InjectedDeviceLoss(idx)
        if fault == "compile":
            # the program-build boundary, before the bucket handle is
            # even looked up
            raise InjectedCompileFailure(idx)
        base = self._base_key(key)
        cfgs = [r.cfg for r in reqs]
        width = self._width(len(cfgs))
        sim = self.cache.get(
            base, cfgs[0],
            members=([bucket_key(r.cfg, r.mode) for r in reqs]
                     if base and base[0] == "canon" else None))
        if fault == "dispatch":
            raise InjectedDispatchFailure(idx)
        leg = self._leg_ticks(reqs)
        if leg is not None and reqs[0].resume is not None:
            # resume legs: the batch re-enters the scan from its
            # checkpoints; filler is replicated from lane 0's snapshot
            # inside the engine.  A mesh change since the snapshot is
            # a MIGRATION — the mesh-independent host carry re-stacks
            # at the new width on the new mesh.
            cks = [r.resume for r in reqs]
            moved = sum(1 for ck in cks
                        if ck.mesh_desc != sim._mesh_entry())
            self._elastic["lanes_migrated"] += moved
            self._elastic["resume_dispatches"] += 1
            if self.store is not None:
                # durable serving: queued requests hold lightweight
                # spill proxies — materialize the real snapshots for
                # dispatch (RAM hit or validated disk reload)
                cks = [self.store.materialize(ck) for ck in cks]
            pending = sim.launch_leg(resume=cks, ticks=leg,
                                     width=width, defer=defer)
            return pending, width
        padded = pad_configs(cfgs, width, self._filler[base])
        if leg is not None:
            pending = sim.launch_leg(configs=padded, ticks=leg,
                                     n_real=len(reqs),
                                     mode=reqs[0].mode, defer=defer)
        elif reqs[0].mode == "bench":
            pending = sim.launch_bench(configs=padded, warmup=False,
                                       n_real=len(reqs), defer=defer)
        else:
            pending = sim.launch(configs=padded, n_real=len(reqs),
                                 warmup=False, defer=defer)
        return pending, width

    def _finish_attempt(self, infl: _Inflight):
        """The resolve half: block + fetch, apply the post-execution
        fault boundaries (latency stall, result poisoning), then
        validate.  Returns the FleetResult or raises."""
        fleet = infl.pending.resolve()
        if infl.fault == "latency":
            dt = self.injector.latency_s(infl.idx)
            self._failures["injected_latency_s"] += dt
            self._sleep(dt)
        if infl.fault == "poison":
            self.injector.poison(fleet, infl.idx)
            self._failures["poisoned_lanes"] += 1
        # result validation: the filler-lane invariant first (a fleet
        # must unstack exactly the real lanes — a mismatch would
        # silently mispair requests and results in the zip below),
        # then per-lane sanity (catches poisoned lanes)
        if len(fleet.lanes) != len(infl.reqs):
            raise DispatchFailed(
                infl.reqs[0].rid, 1, RuntimeError(
                    f"dispatch unstacked {len(fleet.lanes)} lanes for "
                    f"{len(infl.reqs)} requests; filler lanes must "
                    "never be unstacked"))
        if isinstance(fleet, FleetLeg) and not fleet.done:
            # a non-final leg: validate the checkpoints (a poisoned
            # leg fails HERE and retries from the previous snapshot,
            # exactly like any dispatch failure) and hand the leg up
            # for _complete_batch's checkpoint-and-requeue branch
            for r, ck in zip(infl.reqs, fleet.lanes):
                why = validate_checkpoint(r, ck)
                if why is not None:
                    raise PoisonedLaneError(r.rid, why)
            return fleet
        if isinstance(fleet, FleetLeg):
            # final leg: assemble the full-horizon results (pure host
            # work) — validation below covers the stitched chunks, so
            # a poisoned final leg is still caught before completion
            fleet = fleet.results()
        for r, lane in zip(infl.reqs, fleet.lanes):
            why = validate_lane(r, lane)
            if why is not None:
                raise PoisonedLaneError(r.rid, why)
        return fleet

    def _checkpoint_batch(self, key: tuple, reqs: list, leg: FleetLeg,
                          width: int, builds: int, t_q0: float,
                          retries: int) -> None:
        """A non-final leg resolved: snapshot taken.  Attach each
        lane's checkpoint to its request and re-queue the batch under
        the next leg's resume sub-bucket — the handles stay pending
        (continuing work, not a terminal state), and the next
        ``pump``/``flush`` dispatches the next leg.  Counted as a
        dispatch (it is one: a compiled program ran) with its own
        wall-decomposition row."""
        base = self._base_key(key)
        occupancy = len(reqs) / width
        wall = float(leg.wall_seconds)
        alpha = self.slo.wall_ewma_alpha if self.slo is not None else 0.3
        prev = self._bucket_wall.get(key)
        # per QUEUE key: a leg's wall describes its own length, not
        # the base bucket's monolithic dispatch wall
        self._bucket_wall[key] = wall if prev is None \
            else (1.0 - alpha) * prev + alpha * wall
        # wall-per-tick from CLOCK deltas (checkpoint_every_s): this
        # leg ran [prev cut, new cut) ticks
        leg_start = reqs[0].resume.tick if reqs[0].resume is not None \
            else 0
        self._note_tick_wall(base, self.clock() - t_q0,
                             leg.checkpoints[0].tick - leg_start)
        sub = base + (("resume", leg.checkpoints[0].tick),)
        q = self._queues.setdefault(sub, deque())
        for req, ck in zip(reqs, leg.checkpoints):
            # durable serving (PR 12): the cut is journaled and the
            # snapshot write-through-spilled; the request queues with
            # the lightweight proxy so the store's RAM LRU is the
            # ONLY place full snapshots accumulate
            req.resume = ck if self.store is None \
                else self.store.put(req.rid, ck)
            req.bucket = sub
            self._handles[req.rid]._launched = False
            q.append(req)
            self._tenant_note(req.tenant, +1)
        self._elastic["checkpoints_taken"] += 1
        self._dispatches.append({"bucket": base, "batch": len(reqs),
                                 "width": width, "occupancy": occupancy,
                                 "wall_s": wall, "builds": builds,
                                 "pack_s": float(leg.pack_seconds),
                                 "device_wait_s":
                                     float(leg.device_seconds),
                                 "fetch_s": float(leg.fetch_seconds),
                                 "host_s": float(leg.pack_seconds)
                                 + float(leg.fetch_seconds),
                                 "retries": retries})
        self._dispatch_count += 1
        bs = self._bucket_stats[base]
        bs["dispatches"] += 1
        bs["builds"] += builds

    def _complete_batch(self, key: tuple, reqs: list, fleet, width: int,
                        builds: int, t_q0: float,
                        retries: int) -> None:
        if isinstance(fleet, FleetLeg):
            # _finish_attempt converts final legs to FleetResults, so
            # a FleetLeg here is a non-final snapshot: checkpoint +
            # re-queue instead of completing
            self._checkpoint_batch(key, reqs, fleet, width, builds,
                                   t_q0, retries)
            return
        occupancy = len(reqs) / width
        # the dispatch wall decomposes into pack (host staging +
        # dispatch) / execute (device wait — under pipelining this
        # span overlapped the next bucket's pack) / fetch (host
        # transfer + unstack), measured by core/fleet.py at the
        # launch/resolve boundaries — so a mesh speedup lands in the
        # execute column and a staging win in pack/fetch, and none of
        # it needs a block_until_ready on the hot path
        base = self._base_key(key)
        pack = float(fleet.pack_seconds)
        device_wait = float(fleet.device_seconds)
        fetch = float(fleet.fetch_seconds)
        # the REQUEST's run wall: accumulated across every leg of a
        # checkpointed run (FleetLeg.results sums them; equals the
        # decomposition sum on the monolithic path)
        wall = float(fleet.wall_seconds)
        # THIS dispatch's own wall: what the SLO early-flush EWMA and
        # the per-dispatch log row must see — on a final leg the
        # accumulated wall would overstate the next dispatch in this
        # queue by ~the leg count
        leg_wall = pack + device_wait + fetch
        now = self.clock()
        # fold this dispatch's wall into the bucket's EWMA — the
        # early-flush estimate (service/slo.py) for the NEXT partial
        # batch in this bucket
        alpha = self.slo.wall_ewma_alpha if self.slo is not None else 0.3
        prev = self._bucket_wall.get(key)
        self._bucket_wall[key] = leg_wall if prev is None \
            else (1.0 - alpha) * prev + alpha * leg_wall
        leg_start = reqs[0].resume.tick if reqs[0].resume is not None \
            else 0
        self._note_tick_wall(base, now - t_q0,
                             reqs[0].cfg.total_ticks - leg_start)
        for req, lane in zip(reqs, fleet.lanes):
            missed = req.deadline_s is not None and now > req.deadline_s
            if missed:
                self._failures["deadline_misses"] += 1
            legs = req.resume.legs + 1 if req.resume is not None else 1
            req.resume = None       # the run is over; free the snapshot
            if self.store is not None:
                self.store.journal.outcome(req.rid, "completed", lane)
            self._handles.pop(req.rid)._complete(lane, RequestMetrics(
                rid=req.rid, bucket=base, mode=req.mode,
                queue_wait_s=t_q0 - req.submit_s, run_wall_s=wall,
                latency_s=now - req.submit_s, batch=len(reqs),
                padded_batch=width, occupancy=occupancy,
                cache_hit=builds == 0, builds=builds, retries=retries,
                deadline_missed=missed, priority=req.priority,
                tenant=req.tenant, legs=legs))
            self._latencies.append(now - req.submit_s)
            self._note_class_terminal(req, now - req.submit_s, missed)
        self._completed += len(reqs)
        self._dispatches.append({"bucket": base, "batch": len(reqs),
                                 "width": width, "occupancy": occupancy,
                                 "wall_s": leg_wall, "builds": builds,
                                 "pack_s": pack,
                                 "device_wait_s": device_wait,
                                 "fetch_s": fetch,
                                 "host_s": pack + fetch,
                                 "retries": retries})
        self._dispatch_count += 1
        bs = self._bucket_stats[base]
        bs["dispatches"] += 1
        bs["builds"] += builds

    def _degrade_batch(self, key: tuple, reqs: list, t_q0: float,
                       retries: int,
                       last_err: Optional[BaseException] = None) -> None:
        """The degradation ladder's bottom rung: serve each request by
        a direct solo run (service/resilience.py ``solo_run``).  When
        ``degrade_to_solo`` is off — or a solo run itself fails — the
        request fails terminally with a typed DispatchFailed instead;
        either way no handle is left pending."""
        self._failures["degraded_dispatches"] += 1
        if last_err is None:
            last_err = BucketQuarantined(key)
        for req in reqs:
            if not self.degrade_to_solo:
                self._fail_request(req, DispatchFailed(
                    req.rid, max(retries, 1), last_err), cause=last_err)
                continue
            t0 = self.clock()
            legs = 1
            try:
                if req.resume is not None:
                    # even the ladder's bottom rung preserves
                    # checkpointed work: resume the solo continuation
                    # from the lane's snapshot (service/resilience.py
                    # solo_resume) instead of re-running from tick 0
                    legs = req.resume.legs + 1
                    try:
                        res = solo_resume(req)
                    except Exception:
                        # the snapshot could not be resumed — re-run
                        # whole (correct, but checkpointed work is
                        # lost: the one counted restart path)
                        self._elastic["restarted_lanes"] += 1
                        legs = 1
                        res = solo_run(req)
                else:
                    res = solo_run(req)
            except Exception as e:
                self._fail_request(req, DispatchFailed(
                    req.rid, retries + 1, e), cause=e)
                continue
            now = self.clock()
            missed = req.deadline_s is not None and now > req.deadline_s
            if missed:
                self._failures["deadline_misses"] += 1
            self._failures["degraded_requests"] += 1
            req.resume = None
            if self.store is not None:
                self.store.journal.outcome(req.rid, "degraded", res)
            self._handles.pop(req.rid)._complete(res, RequestMetrics(
                rid=req.rid, bucket=self._base_key(key), mode=req.mode,
                queue_wait_s=t_q0 - req.submit_s,
                run_wall_s=now - t0, latency_s=now - req.submit_s,
                batch=1, padded_batch=1, occupancy=1.0,
                cache_hit=False, builds=0, retries=retries,
                degraded=True, deadline_missed=missed,
                priority=req.priority, tenant=req.tenant, legs=legs))
            self._latencies.append(now - req.submit_s)
            self._note_class_terminal(req, now - req.submit_s, missed)
            self._completed += 1

    def _degrade_mesh(self) -> None:
        """One rung down the ladder, axis-aware (PR 19): on a 2-D
        mesh a device loss drops a PEER shard first — the peer axis
        halves, every lane keeps serving, and each simulation's peer
        tables re-shard across the survivors at the next dispatch
        (checkpoints are mesh-independent host numpy, so nothing
        restarts) — down to a 1-D lane mesh, then lane devices drop
        one at a time (to no mesh at all below two devices).  Rebinds
        the program cache so the bucket's next attempt rebuilds on the
        smaller mesh through the existing mesh-keyed caches — sibling
        buckets on other services keep their programs (eviction is
        per-handle exact, core/fleet.py ``evict_programs``)."""
        from ..parallel.fleet_mesh import mesh_axis_sizes, shrink_mesh
        self.mesh = shrink_mesh(self.mesh)
        self.n_lanes, self.n_peers, _ = mesh_axis_sizes(self.mesh)
        self.n_devices = (int(self.mesh.devices.size)
                          if self.mesh is not None else 1)
        self.cache.rebind_mesh(self.mesh)
        self._failures["mesh_rebuilds"] += 1

    def _grow_mesh(self) -> None:
        """One rung UP the ladder (PR 8): re-extend the lane mesh
        toward the full-strength device set captured at construction
        (``parallel.fleet_mesh.grow_mesh``) and re-key the program
        cache — a descriptor that served before the loss finds its
        retained handles and compiled programs warm (``rebind_mesh``
        re-keys rather than evicts), so a shrink -> grow cycle costs
        zero rebuilds.  Queued and checkpointed lanes migrate onto the
        wider mesh at their next dispatch (the snapshots are
        mesh-independent host numpy).  Axis-aware (PR 19): toward a
        2-D full shape the ladder restores the LANE axis first, then
        doubles the peer axis back toward full strength — the exact
        inverse of ``_degrade_mesh``, and because every rung selects
        the same flat device PREFIX, a grow-back lands on descriptors
        the shrink already served (warm re-key, zero rebuilds).  No-op
        on a service that never had a mesh, or one already at full
        strength."""
        from ..parallel.fleet_mesh import grow_mesh, mesh_axis_sizes
        new = grow_mesh(self.mesh, self._full_devices,
                        full_shape=self._full_shape,
                        full_axes=self._full_axes)
        new_d = int(new.devices.size) if new is not None else 1
        if new is self.mesh or (new_d == self.n_devices
                                and mesh_axis_sizes(new) ==
                                mesh_axis_sizes(self.mesh)):
            return
        self.mesh = new
        self.n_lanes, self.n_peers, _ = mesh_axis_sizes(new)
        self.n_devices = new_d
        self.cache.rebind_mesh(new)
        self._elastic["mesh_grows"] += 1
        self._failures["mesh_rebuilds"] += 1

    def _fail_request(self, req, error: BaseException,
                      cause: Optional[BaseException] = None) -> None:
        if cause is not None and error.__cause__ is None:
            error.__cause__ = cause
        self._failed += 1
        self._failures["failed_requests"] += 1
        self._class_stat(req.priority)["failed"] += 1
        if self.store is not None:
            self.store.journal.outcome(req.rid, "failed",
                                       error=type(error).__name__)
        self._handles.pop(req.rid)._fail(error)

    def _drop_expired(self, reqs: list, now: float) -> list:
        """Fail (terminally, typed) the requests whose deadline has
        passed; returns the still-live ones."""
        live = []
        for r in reqs:
            if r.deadline_s is not None and now >= r.deadline_s:
                self._failures["deadline_misses"] += 1
                self._class_stat(r.priority)["deadline_misses"] += 1
                self._fail_request(r, DeadlineExceeded(
                    r.rid, now - r.submit_s, r.deadline_s - r.submit_s))
            else:
                live.append(r)
        return live

    def _tenant_note(self, tenant: Optional[str], delta: int) -> None:
        """Maintain the per-tenant QUEUED count (quota admission reads
        it O(1)); entries drop to keep the dict bounded by the live
        tenant set."""
        if tenant is None:
            return
        c = self._tenant_queued.get(tenant, 0) + delta
        if c > 0:
            self._tenant_queued[tenant] = c
        else:
            self._tenant_queued.pop(tenant, None)

    # ---- per-priority-class accounting --------------------------------
    def _class_stat(self, priority: str) -> dict:
        return self._class_stats.setdefault(
            priority, {"completed": 0, "failed": 0,
                       "deadline_misses": 0})

    def _note_class_terminal(self, req, latency_s: float,
                             missed: bool) -> None:
        """One completed (or degraded-completed) request's per-class
        bookkeeping: its own bounded latency window + counters."""
        self._class_lat.setdefault(
            req.priority,
            deque(maxlen=self._stats_window)).append(latency_s)
        cs = self._class_stat(req.priority)
        cs["completed"] += 1
        if missed:
            cs["deadline_misses"] += 1

    def _expire_deadlines(self, now: float) -> None:
        """Queue-side deadline expiry (pump/flush): a request that can
        no longer make its deadline fails fast instead of wasting a
        lane.  Free until the first deadline-carrying request is
        admitted — a deadline-less service never pays the queue scan
        on its admission path."""
        if not self._has_deadlines:
            return
        for key in list(self._queues):
            q = self._queues[key]
            if not q or all(r.deadline_s is None for r in q):
                continue
            before = list(q)
            live = self._drop_expired(before, now)
            if len(live) != len(q):
                kept = {r.rid for r in live}
                for r in before:
                    if r.rid not in kept:
                        self._tenant_note(r.tenant, -1)
                q.clear()
                q.extend(live)

    @staticmethod
    def _min_remaining(reqs: list, now: float) -> Optional[float]:
        rem = [r.deadline_s - now for r in reqs
               if r.deadline_s is not None]
        return min(rem) if rem else None

    # ---- warm + metrics ----------------------------------------------
    def warm(self, cfg: SimConfig, mode: str = "trace") -> None:
        """Pre-build and execute a bucket's full-batch program.

        Compiles (and runs once, on ``max_batch`` filler lanes with a
        single unstacked lane) the widest program ``cfg``'s bucket can
        dispatch, without touching request metrics — so a
        latency-sensitive caller can take the build cost up front.
        Under ``pad_policy="full"`` (the default: one width per
        bucket) a warmed bucket never builds on dispatch again; under
        ``"pow2"``/``"none"`` this warms the full-batch width only —
        partial-batch widths still compile on first use.  Warmth is
        also bounded by the program cache: warming more than
        ``cache_max_entries`` distinct buckets LRU-evicts the earliest
        ones (programs included), so size the bound to the working set
        before a warm sweep.
        """
        key = self._bucket(cfg, mode)
        sim = self.cache.get(
            key, cfg,
            members=([bucket_key(cfg, mode)]
                     if key and key[0] == "canon" else None))
        self._filler.setdefault(key, cfg)
        self._bucket_stats.setdefault(key, {"requests": 0, "dispatches": 0,
                                            "builds": 0})
        width = self._width(self.capacity)
        padded = pad_configs([cfg], width, cfg)
        builds0 = run_build_count()
        c_warm0 = self.clock()
        first_leg = None
        if self.checkpoint_every is not None \
                and (cfg.model == "overlay" or mode == "trace"):
            end0 = cut_for_budget(cfg, 0, self.checkpoint_every)
            if end0 < cfg.total_ticks:
                first_leg = end0
        if first_leg is not None:
            # checkpointed serving dispatches LEG-length programs, not
            # the monolithic whole-run one — warm the same leg chain
            # the scheduler will run (one program per distinct leg
            # length), so elastic dispatches don't compile in-band
            leg = sim.run_leg(configs=padded, n_real=1,
                              ticks=first_leg, mode=mode)
            while not leg.done:
                nxt = cut_for_budget(cfg, leg.checkpoints[0].tick,
                                     self.checkpoint_every)
                leg = sim.run_leg(resume=leg.checkpoints,
                                  ticks=nxt - leg.checkpoints[0].tick,
                                  width=width)
            wall = float(leg.checkpoints[0].wall_seconds)
        elif mode == "bench":
            wall = float(sim.run_bench(configs=padded, warmup=False,
                                       n_real=1).wall_seconds)
        else:
            wall = float(sim.run(configs=padded, n_real=1,
                                 warmup=False).wall_seconds)
        self._bucket_stats[key]["builds"] += run_build_count() - builds0
        # seed the bucket's dispatch-wall EWMA so the SLO early-flush
        # estimate has a real number before the first live dispatch.
        # A warm run that just compiled reports an inflated wall —
        # which errs CONSERVATIVE (flush earlier than strictly needed)
        # and the EWMA converges within a few live dispatches
        self._bucket_wall.setdefault(key, wall)
        # seed the wall-per-tick estimate for checkpoint_every_s from
        # CLOCK deltas (deterministic under a virtual clock); the
        # just-compiled inflation again errs conservative — shorter
        # first legs, converging within a few dispatches
        self._tick_wall.setdefault(
            key, max(self.clock() - c_warm0, 0.0)
            / max(cfg.total_ticks, 1))
        if self.checkpoint_every is None \
                and self.checkpoint_every_s is not None \
                and (cfg.model == "overlay" or mode == "trace"):
            # the seconds budget resolves to ticks only AFTER this
            # warm seeded the wall-per-tick estimate — warm the same
            # leg chain the first live dispatch will now run, so a
            # warmed seconds-budget bucket neither compiles leg
            # programs in-band nor folds compile time into its first
            # EWMA samples.  (Later dispatches may re-quantize to a
            # different cut as the EWMA converges; cuts are few, so
            # the chain covers the common lengths.)
            budget = self._ticks_for_seconds(self._base_key(key))
            end0 = cut_for_budget(cfg, 0, budget) \
                if budget is not None else cfg.total_ticks
            if end0 < cfg.total_ticks:
                builds1 = run_build_count()
                leg = sim.run_leg(configs=padded, n_real=1,
                                  ticks=end0, mode=mode)
                while not leg.done:
                    nxt = cut_for_budget(cfg, leg.checkpoints[0].tick,
                                         budget)
                    leg = sim.run_leg(
                        resume=leg.checkpoints,
                        ticks=nxt - leg.checkpoints[0].tick,
                        width=width)
                self._bucket_stats[key]["builds"] += \
                    run_build_count() - builds1

    def stats(self) -> dict:
        """Service-level serving metrics (the BENCH json schema).

        ``latency`` percentiles and ``mean_occupancy`` describe the
        bounded stats window (see ``stats_window``); request/dispatch
        counters are lifetime-exact.  ``mean_occupancy`` is the
        unweighted mean over dispatches (each dispatch pays its own
        program, so a half-empty batch counts half no matter how many
        requests rode it).  ``program_hit_rate`` is the fraction of
        windowed dispatches that reused an already-built compiled
        program (zero new whole-run builds) — the compiled-program
        cache metric; the ProgramCache ``hit_rate`` below it only
        counts bucket-handle reuse.

        The open-loop traffic plane (PR 7) ADDS — without changing any
        existing aggregate field — ``latency_p99_s``, per-priority-
        class windows under ``classes`` (each class keeps its OWN
        bounded latency window, so sustained mixed traffic cannot
        smear one class's tail into another's percentiles),
        ``slo_early_flushes``, and per-tenant shed counts under
        ``tenant_shed``.
        """
        lat = np.asarray(self._latencies, dtype=np.float64)
        occ = np.asarray([d["occupancy"] for d in self._dispatches])
        hits = sum(1 for d in self._dispatches if d["builds"] == 0)
        dev = np.asarray([d["device_wait_s"] for d in self._dispatches])
        pack = np.asarray([d["pack_s"] for d in self._dispatches])
        fetch = np.asarray([d["fetch_s"] for d in self._dispatches])
        host = np.asarray([d["host_s"] for d in self._dispatches])
        walls = dev + host
        mean_pack = round(float(pack.mean()), 6) if pack.size else 0.0
        mean_fetch = round(float(fetch.mean()), 6) if fetch.size else 0.0
        out = {
            "requests": self._next_rid,
            "completed": self._completed,
            "failed": self._failed,
            "pending": self.pending,
            "in_flight": self.in_flight,
            "pipeline": self.pipeline,
            # the ring plane (PR 17): configured depth, how deep each
            # bucket's ring is stacked RIGHT NOW (reqs per in-flight
            # batch, oldest first — empty dict when nothing is in
            # flight), and how often a dispatch found its ring full
            # and had to wait on (displace) the oldest slot.  Like
            # ``in_flight``, a read-only view: stats() never resolves.
            "pipeline_depth": self.pipeline_depth,
            "in_flight_by_bucket": {
                repr(k): [len(i.reqs) for i in ring]
                for k, ring in self._rings.items() if ring},
            "ring_stalls": self._ring_stalls,
            "dispatches": self._dispatch_count,
            "mean_occupancy": round(float(occ.mean()), 4) if occ.size else 0.0,
            "latency_p50_s": round(float(np.percentile(lat, 50)), 6)
            if lat.size else 0.0,
            "latency_p95_s": round(float(np.percentile(lat, 95)), 6)
            if lat.size else 0.0,
            "latency_p99_s": round(float(np.percentile(lat, 99)), 6)
            if lat.size else 0.0,
            "program_hit_rate": round(hits / len(self._dispatches), 4)
            if self._dispatches else 0.0,
            # where the per-dispatch wall goes, decomposed honestly
            # (PR 6): pack (host staging + async dispatch) / execute
            # (device wait, ``mean_device_wait_s`` — the mesh lever
            # moves this, and pipelining overlaps the NEXT pack under
            # it) / fetch (host transfer + unstack).  ``mean_host_s``
            # = pack + fetch EXACTLY as reported: it is the sum of the
            # two rounded columns (independently rounding all three
            # breaks the identity by up to 1.5e-6); the old key is
            # kept for BENCH-json continuity.
            "mean_pack_s": mean_pack,
            "mean_device_wait_s": round(float(dev.mean()), 6)
            if dev.size else 0.0,
            "mean_fetch_s": mean_fetch,
            "mean_host_s": round(mean_pack + mean_fetch, 6),
            "device_wait_frac": round(float(dev.sum() / walls.sum()), 4)
            if dev.size and walls.sum() > 0 else 0.0,
            "cache": self.cache.stats(),
            "max_batch": self.max_batch,
            "pad_policy": self.pad_policy,
            "devices": self.n_devices,
            # the 2-D decomposition (PR 19): batch shards x peer-table
            # shards at the CURRENT elasticity rung; devices ==
            # lanes * peers whenever a mesh rides
            "lanes": self.n_lanes,
            "peers": self.n_peers,
            "capacity": self.capacity,
            # the failure domain (PR 5): lifetime-exact counters like
            # requests/dispatches above; the windowed per-dispatch
            # view carries "retries" in each _dispatches entry
            "failures": {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in self._failures.items()},
            "breaker_open_buckets":
                self.breaker.open_buckets(self.clock()),
            # the SLO / traffic plane (PR 7): deadline-aware early
            # dispatches and per-tenant admission shedding
            "slo_early_flushes": self._early_flushes,
            "tenant_shed": dict(sorted(self._tenant_shed.items())),
            "wfq_served": dict(sorted(self._wfq_served.items())),
            # the elasticity plane (PR 8): mesh grows, segment-
            # boundary checkpoints, lane migrations across mesh
            # rebuilds, resume dispatches, and the restarted-from-
            # tick-0 counter the elastic replay gate pins to 0
            "elastic": dict(self._elastic),
            "checkpoint_every": self.checkpoint_every,
            "checkpoint_every_s": self.checkpoint_every_s,
            # the compile-surface plane (PR 16): whether requests
            # bucket by canonical equivalence class; the per-class
            # collapse map rides in "cache"["classes"]
            "canonicalize": self.canonicalize,
            # the durability plane (PR 12, gossip_protocol_tpu/store/):
            # spill/journal/recovery counters when a RunStore rides;
            # None on a store-less service (the key is always present
            # so dashboards need no schema branch)
            "durability": (self.store.stats()
                           if self.store is not None else None),
        }
        # per-priority-class view: each class's OWN windowed
        # percentiles + lifetime terminal counters (completed counts
        # degraded completions; failed counts typed failures incl.
        # queue-side deadline expiry)
        classes = {}
        for name in sorted(set(self._class_stats) | set(self._class_lat)):
            cs = dict(self._class_stat(name))
            w = np.asarray(self._class_lat.get(name, ()),
                           dtype=np.float64)
            terminal = cs["completed"] + cs["failed"]
            classes[name] = {
                **cs,
                "deadline_miss_rate":
                    round(cs["deadline_misses"] / terminal, 4)
                    if terminal else 0.0,
                "latency_p50_s": round(float(np.percentile(w, 50)), 6)
                if w.size else 0.0,
                "latency_p99_s": round(float(np.percentile(w, 99)), 6)
                if w.size else 0.0,
                "window": int(w.size),
            }
        out["classes"] = classes
        out["buckets"] = {repr(k): dict(v)
                          for k, v in self._bucket_stats.items()}
        return out
