"""Continuous-batching request scheduler for simulation serving.

The engine side of serving landed in core/fleet.py: B same-shape
simulations through ONE compiled program, ~3.4x the wall of B=8
sequential runs on this CPU image (docs/PERF.md §8).  What was missing
is the layer every inference stack puts above such an engine (Orca's
iteration-level scheduler, vLLM's waiting/running queues): something
that accepts a *stream* of heterogeneous requests and keeps the
batched engine fed.  This module is that layer, sized to this
framework's unit of work — a whole simulation run, not a decode step,
so batches form per request stream rather than per iteration:

* **admission** — ``submit()`` validates the mode, stamps the request,
  and enqueues it under its shape bucket (service/bucket.py: shape
  key + segment-plan signature + mode); heterogeneous streams coexist
  as parallel queues rather than poisoning one batch.
* **flush policies** — a bucket dispatches when it has ``max_batch``
  requests (the B≈8-16 knee of the CPU batching curve, PERF §8), when
  its oldest request has waited ``max_wait_s`` (bounded latency under
  trickle traffic), or when ``flush()``/``drain()``/``result()``
  forces it.
* **padding** — a partial batch is padded to the bucket's compiled
  width with inert filler lanes (replicas of the bucket's first
  config) so one program per bucket serves every dispatch; filler is
  masked out device-side and never unstacked (core/fleet.py
  ``n_real``), so results stay bit-identical to solo runs.
* **program cache** — bucket key -> FleetSimulation (service/cache.py)
  with hit/miss/build counters over ``core.tick.run_build_count``.
* **metrics** — per-request queue wait / run wall / latency, per-
  dispatch occupancy, and service aggregates (p50/p95 latency, mean
  occupancy, cache hit rate) via :meth:`FleetService.stats`.

The service is synchronous and single-threaded by design: requests
are admitted from one host loop (a trace replay, the grader, a bench
driver) and time-based flushes happen cooperatively inside
``submit``/``pump`` — there is no background thread to race the JAX
runtime.  ``drain()`` (or exiting the context manager) flushes
everything outstanding.

Failure model (PR 5, docs/SERVING.md "Failure model"): dispatching is
ATOMIC — every request popped for a dispatch reaches a terminal state
(completed, degraded to a solo run, or failed with a typed error on
its handle) before the dispatch returns; nothing is ever re-queued
into limbo.  The machinery is service/resilience.py (bounded retry
with seeded exponential backoff, per-request deadlines, a per-bucket
circuit breaker that quarantines repeat offenders onto the solo
fallback, queue-depth admission control with typed shedding) plus
graceful mesh degradation: a device loss shrinks the lane mesh
(parallel/fleet_mesh.py ``shrink_mesh``) and rebuilds the bucket's
programs through the mesh-keyed caches.  All of it is exercised
deterministically by the seeded fault plane in service/faults.py.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Optional

import numpy as np

from ..config import SimConfig
from ..core.tick import run_build_count
from .bucket import bucket_key, pad_configs
from .cache import ProgramCache
from .faults import FaultInjector, InjectedCompileFailure, \
    InjectedDeviceLoss, InjectedDispatchFailure
from .resilience import (BreakerPolicy, BucketQuarantined, CircuitBreaker,
                         DeadlineExceeded, DispatchFailed,
                         PoisonedLaneError, RetryPolicy, ShedRejection,
                         solo_run, validate_lane)
from .types import MODES, RequestHandle, RequestMetrics, SimRequest

#: padding policies: "full" pads every dispatch to ``max_batch`` (one
#: compiled width — and so at most one build — per bucket); "pow2"
#: pads to the next power of two (less filler work, up to
#: log2(max_batch)+1 widths per bucket); "none" never pads (a width
#: per distinct batch size).
PAD_POLICIES = ("full", "pow2", "none")


class FleetService:
    """Continuous-batching scheduler over :class:`FleetSimulation`.

    >>> svc = FleetService(max_batch=8)
    >>> handles = [svc.submit(cfg, seed=s) for s in range(20)]
    >>> svc.drain()
    >>> results = [h.result() for h in handles]   # SimResult per request

    ``max_wait_s`` bounds queueing latency under trickle traffic; it
    is enforced cooperatively (checked on every ``submit``/``pump``
    against ``clock()``), not by a background thread.

    ``mesh`` (a 1-D lane mesh, ``parallel.fleet_mesh.make_lane_mesh``)
    serves every dispatch from the whole mesh: ``max_batch`` becomes
    the PER-DEVICE lane width and the dispatch :attr:`capacity` is
    ``max_batch x n_devices``; pad widths are rounded up to a
    shard-divisible lane count (every pad policy, so a partial batch
    always divides over the mesh), and the program cache keys gain the
    mesh descriptor so a device-count change can never be served a
    stale program.
    """

    def __init__(self, max_batch: int = 8,
                 max_wait_s: Optional[float] = None,
                 pad_policy: str = "full", block_size: int = 128,
                 chunk_ticks: Optional[int] = None, clock=time.perf_counter,
                 stats_window: int = 1 << 14, mesh=None,
                 cache_max_entries: Optional[int] = 64,
                 injector: Optional[FaultInjector] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[BreakerPolicy] = None,
                 max_queue_depth: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 degrade_to_solo: bool = True, sleep=time.sleep):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if pad_policy not in PAD_POLICIES:
            raise ValueError(f"unknown pad_policy {pad_policy!r}; "
                             f"expected one of {PAD_POLICIES}")
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1 or None, "
                             f"got {max_queue_depth}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.pad_policy = pad_policy
        self.mesh = mesh
        self.n_devices = int(mesh.devices.size) if mesh is not None else 1
        self.clock = clock
        self.cache = ProgramCache(block_size=block_size,
                                  chunk_ticks=chunk_ticks, mesh=mesh,
                                  max_entries=cache_max_entries)
        # failure plane: the (optional) deterministic fault injector
        # and the machinery that survives it (service/resilience.py)
        self.injector = injector
        self.retry = retry if retry is not None else RetryPolicy()
        self.breaker = CircuitBreaker(breaker if breaker is not None
                                      else BreakerPolicy())
        self.max_queue_depth = max_queue_depth
        self.default_deadline_s = default_deadline_s
        self.degrade_to_solo = degrade_to_solo
        self._sleep = sleep
        self._has_deadlines = False   # gates the per-pump queue scan
        self._attempts = 0      # dispatch-attempt counter = the fault
        #                         schedule's index (service/faults.py)
        self._queues: dict[tuple, deque] = {}
        self._handles: dict[int, RequestHandle] = {}
        self._filler: dict[tuple, SimConfig] = {}
        self._next_rid = 0
        self._completed = 0
        self._failed = 0
        # service aggregates over a bounded sliding window: a
        # long-lived stream must not grow host memory per request, so
        # stats() percentiles/means describe the last ``stats_window``
        # latencies and dispatches (counters stay lifetime-exact)
        self._latencies: deque = deque(maxlen=stats_window)
        self._dispatches: deque = deque(maxlen=max(1, stats_window // 8))
        self._dispatch_count = 0
        self._bucket_stats: dict[tuple, dict] = {}
        # failure-domain counters (lifetime-exact, like the request/
        # dispatch counters; the windowed view rides the _dispatches
        # entries' "retries" field)
        self._failures = {
            "retries": 0, "backoff_s": 0.0, "deadline_misses": 0,
            "shed": 0, "breaker_opens": 0, "degraded_dispatches": 0,
            "degraded_requests": 0, "failed_requests": 0,
            "device_losses": 0, "mesh_rebuilds": 0,
            "faults_injected": 0, "poisoned_lanes": 0,
            "injected_latency_s": 0.0,
        }

    # ---- admission ---------------------------------------------------
    def submit(self, cfg: SimConfig, seed: Optional[int] = None,
               mode: str = "trace",
               deadline_s: Optional[float] = None) -> RequestHandle:
        """Admit one simulation request; returns immediately.

        ``seed`` is sugar for ``cfg.replace(seed=seed)``.  Admission
        also runs the cooperative flush pass, so a submit can complete
        earlier requests (its own too, when it fills a batch).

        ``deadline_s`` (or the service's ``default_deadline_s``) is a
        relative latency budget on the service clock: a request still
        queued past it fails fast with :class:`DeadlineExceeded`; one
        that completes late is delivered with
        ``metrics.deadline_missed`` set.  When the queue already holds
        ``max_queue_depth`` requests, admission sheds with the typed
        :class:`ShedRejection` — load is never shed by silently
        dropping something already queued.
        """
        if mode not in MODES:
            raise ValueError(f"unknown mode {mode!r}; expected one "
                             f"of {MODES}")
        if self.max_queue_depth is not None \
                and self.pending >= self.max_queue_depth:
            self._failures["shed"] += 1
            raise ShedRejection(self.pending, self.max_queue_depth)
        if seed is not None:
            cfg = cfg.replace(seed=int(seed))
        key = bucket_key(cfg, mode)
        now = self.clock()
        budget = deadline_s if deadline_s is not None \
            else self.default_deadline_s
        req = SimRequest(rid=self._next_rid, cfg=cfg, mode=mode,
                         bucket=key, submit_s=now,
                         deadline_s=(now + budget
                                     if budget is not None else None))
        if req.deadline_s is not None:
            self._has_deadlines = True
        self._next_rid += 1
        handle = RequestHandle(request=req, _service=self)
        self._handles[req.rid] = handle
        self._queues.setdefault(key, deque()).append(req)
        self._filler.setdefault(key, cfg)
        self._bucket_stats.setdefault(key, {"requests": 0, "dispatches": 0,
                                            "builds": 0})
        self._bucket_stats[key]["requests"] += 1
        self.pump()
        return handle

    @property
    def capacity(self) -> int:
        """Lanes one dispatch can carry: ``max_batch`` per device,
        times the lane mesh (1 without a mesh)."""
        return self.max_batch * self.n_devices

    # ---- flush policies ----------------------------------------------
    def pump(self) -> int:
        """One cooperative scheduling pass; returns dispatches made.

        Flushes every bucket that is full (:attr:`capacity`) and every
        bucket whose oldest request has waited past ``max_wait_s``.
        """
        n = 0
        now = self.clock()
        self._expire_deadlines(now)
        for key in list(self._queues):
            q = self._queues[key]
            while len(q) >= self.capacity:
                self._dispatch(key)
                n += 1
            if (q and self.max_wait_s is not None
                    and now - q[0].submit_s >= self.max_wait_s):
                self._dispatch(key)
                n += 1
        return n

    def flush(self, bucket: Optional[tuple] = None) -> int:
        """Dispatch everything pending (in one bucket, or all)."""
        n = 0
        self._expire_deadlines(self.clock())
        keys = [bucket] if bucket is not None else list(self._queues)
        for key in keys:
            while self._queues.get(key):
                self._dispatch(key)
                n += 1
        return n

    def drain(self) -> int:
        """Flush all buckets; the stream is over (for now)."""
        return self.flush()

    @property
    def pending(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.drain()
        return False

    # ---- dispatch ----------------------------------------------------
    def _width(self, k: int) -> int:
        """Compiled lane width for a ``k``-request batch.

        Every policy's width is rounded UP to a multiple of the mesh
        size (a lane-sharded fleet needs ``B % n_devices == 0``;
        without a mesh this is a no-op), and under a mesh the "full"
        width is the whole-mesh :attr:`capacity` — one compiled width,
        and so at most one build, per bucket either way.
        """
        if self.pad_policy == "none":
            w = k
        elif self.pad_policy == "pow2":
            w = min(self.capacity, 1 << (k - 1).bit_length())
        else:
            w = self.capacity
        # a mesh shrink mid-flight can leave an already-popped batch
        # wider than the NEW capacity; the width must still cover it
        w = max(w, k)
        d = self.n_devices
        return -(-w // d) * d

    def _dispatch(self, key: tuple) -> None:
        """Pop one batch and resolve it ATOMICALLY: every popped
        request reaches a terminal state (completed, degraded, or
        failed on its handle) before this returns.  Only non-Exception
        escapes (KeyboardInterrupt, SystemExit) re-queue the
        still-unresolved requests at the queue front and propagate."""
        q = self._queues[key]
        reqs = [q.popleft() for _ in range(min(len(q), self.capacity))]
        try:
            self._serve_batch(key, reqs)
        except BaseException:
            unresolved = [r for r in reqs if r.rid in self._handles]
            q.extendleft(reversed(unresolved))
            raise

    # ---- resilient dispatch (service/resilience.py) ------------------
    def _serve_batch(self, key: tuple, reqs: list) -> None:
        now = self.clock()
        reqs = self._drop_expired(reqs, now)
        if not reqs:
            return
        t_q0 = now              # queue wait ends at the first attempt
        if not self.breaker.allow(key, now):
            # quarantined bucket: straight to the ladder's bottom rung
            self._degrade_batch(key, reqs, t_q0, retries=0)
            return
        attempt = 0
        last_err: Optional[BaseException] = None
        while True:
            self._attempts += 1
            idx = self._attempts
            fault = (self.injector.plan(idx)
                     if self.injector is not None else None)
            if fault is not None:
                self._failures["faults_injected"] += 1
            builds0 = run_build_count()
            t0 = self.clock()
            try:
                fleet, width = self._attempt(key, reqs, fault, idx)
                wall = self.clock() - t0
                builds = run_build_count() - builds0
                self.breaker.record_success(key)
                self._complete_batch(key, reqs, fleet, width, wall,
                                     builds, t_q0, retries=attempt)
                return
            except InjectedDeviceLoss as e:
                self._failures["device_losses"] += 1
                if self.mesh is not None:
                    self._degrade_mesh()
                last_err = e
            except Exception as e:
                last_err = e
            if self.breaker.record_failure(key, self.clock()):
                self._failures["breaker_opens"] += 1
            attempt += 1
            now = self.clock()
            reqs = self._drop_expired(reqs, now)
            if not reqs:
                return
            backoff = self.retry.backoff_s(attempt, salt=idx)
            remaining = self._min_remaining(reqs, now)
            if attempt > self.retry.max_retries or \
                    (remaining is not None and backoff >= remaining):
                break
            self._failures["retries"] += 1
            self._failures["backoff_s"] += backoff
            self._sleep(backoff)
        # retries exhausted: degrade to the solo fallback (or fail
        # terminally when the fallback is disabled)
        self._degrade_batch(key, reqs, t_q0, retries=attempt,
                            last_err=last_err)

    def _attempt(self, key: tuple, reqs: list, fault: Optional[str],
                 idx: int):
        """One dispatch attempt, with the fault plane consulted at
        each boundary; returns ``(fleet, width)`` or raises."""
        if fault == "device_loss":
            raise InjectedDeviceLoss(idx)
        if fault == "compile":
            # the program-build boundary, before the bucket handle is
            # even looked up
            raise InjectedCompileFailure(idx)
        cfgs = [r.cfg for r in reqs]
        width = self._width(len(cfgs))
        padded = pad_configs(cfgs, width, self._filler[key])
        sim = self.cache.get(key, cfgs[0])
        if fault == "dispatch":
            raise InjectedDispatchFailure(idx)
        if reqs[0].mode == "bench":
            fleet = sim.run_bench(configs=padded, warmup=False,
                                  n_real=len(reqs))
        else:
            fleet = sim.run(configs=padded, n_real=len(reqs),
                            warmup=False)
        if fault == "latency":
            dt = self.injector.latency_s(idx)
            self._failures["injected_latency_s"] += dt
            self._sleep(dt)
        if fault == "poison":
            self.injector.poison(fleet, idx)
            self._failures["poisoned_lanes"] += 1
        # result validation: the filler-lane invariant first (a fleet
        # must unstack exactly the real lanes — a mismatch would
        # silently mispair requests and results in the zip below),
        # then per-lane sanity (catches poisoned lanes)
        if len(fleet.lanes) != len(reqs):
            raise DispatchFailed(
                reqs[0].rid, 1, RuntimeError(
                    f"dispatch unstacked {len(fleet.lanes)} lanes for "
                    f"{len(reqs)} requests; filler lanes must never "
                    "be unstacked"))
        for r, lane in zip(reqs, fleet.lanes):
            why = validate_lane(r, lane)
            if why is not None:
                raise PoisonedLaneError(r.rid, why)
        return fleet, width

    def _complete_batch(self, key: tuple, reqs: list, fleet, width: int,
                        wall: float, builds: int, t_q0: float,
                        retries: int) -> None:
        occupancy = len(reqs) / width
        # split the dispatch wall: device-wait (program execution,
        # core/fleet.py times it around dispatch+block_until_ready) vs
        # host stack/unstack — so a mesh speedup shows up where it
        # lands (the device column) instead of vanishing into one
        # number (stats()["mean_device_wait_s"]/["mean_host_s"])
        device_wait = min(wall, float(fleet.device_seconds))
        now = self.clock()
        for req, lane in zip(reqs, fleet.lanes):
            missed = req.deadline_s is not None and now > req.deadline_s
            if missed:
                self._failures["deadline_misses"] += 1
            self._handles.pop(req.rid)._complete(lane, RequestMetrics(
                rid=req.rid, bucket=key, mode=req.mode,
                queue_wait_s=t_q0 - req.submit_s, run_wall_s=wall,
                latency_s=now - req.submit_s, batch=len(reqs),
                padded_batch=width, occupancy=occupancy,
                cache_hit=builds == 0, builds=builds, retries=retries,
                deadline_missed=missed))
            self._latencies.append(now - req.submit_s)
        self._completed += len(reqs)
        self._dispatches.append({"bucket": key, "batch": len(reqs),
                                 "width": width, "occupancy": occupancy,
                                 "wall_s": wall, "builds": builds,
                                 "device_wait_s": device_wait,
                                 "host_s": max(0.0, wall - device_wait),
                                 "retries": retries})
        self._dispatch_count += 1
        bs = self._bucket_stats[key]
        bs["dispatches"] += 1
        bs["builds"] += builds

    def _degrade_batch(self, key: tuple, reqs: list, t_q0: float,
                       retries: int,
                       last_err: Optional[BaseException] = None) -> None:
        """The degradation ladder's bottom rung: serve each request by
        a direct solo run (service/resilience.py ``solo_run``).  When
        ``degrade_to_solo`` is off — or a solo run itself fails — the
        request fails terminally with a typed DispatchFailed instead;
        either way no handle is left pending."""
        self._failures["degraded_dispatches"] += 1
        if last_err is None:
            last_err = BucketQuarantined(key)
        for req in reqs:
            if not self.degrade_to_solo:
                self._fail_request(req, DispatchFailed(
                    req.rid, max(retries, 1), last_err), cause=last_err)
                continue
            t0 = self.clock()
            try:
                res = solo_run(req)
            except Exception as e:
                self._fail_request(req, DispatchFailed(
                    req.rid, retries + 1, e), cause=e)
                continue
            now = self.clock()
            missed = req.deadline_s is not None and now > req.deadline_s
            if missed:
                self._failures["deadline_misses"] += 1
            self._failures["degraded_requests"] += 1
            self._handles.pop(req.rid)._complete(res, RequestMetrics(
                rid=req.rid, bucket=key, mode=req.mode,
                queue_wait_s=t_q0 - req.submit_s,
                run_wall_s=now - t0, latency_s=now - req.submit_s,
                batch=1, padded_batch=1, occupancy=1.0,
                cache_hit=False, builds=0, retries=retries,
                degraded=True, deadline_missed=missed))
            self._latencies.append(now - req.submit_s)
            self._completed += 1

    def _degrade_mesh(self) -> None:
        """One rung down the ladder: drop a device from the lane mesh
        (to no mesh at all below two devices) and rebind the program
        cache, so the bucket's next attempt rebuilds on the smaller
        mesh through the existing mesh-keyed caches — sibling buckets
        on other services keep their programs (eviction is per-handle
        exact, core/fleet.py ``evict_programs``)."""
        from ..parallel.fleet_mesh import shrink_mesh
        self.mesh = shrink_mesh(self.mesh)
        self.n_devices = (int(self.mesh.devices.size)
                          if self.mesh is not None else 1)
        self.cache.rebind_mesh(self.mesh)
        self._failures["mesh_rebuilds"] += 1

    def _fail_request(self, req, error: BaseException,
                      cause: Optional[BaseException] = None) -> None:
        if cause is not None and error.__cause__ is None:
            error.__cause__ = cause
        self._failed += 1
        self._failures["failed_requests"] += 1
        self._handles.pop(req.rid)._fail(error)

    def _drop_expired(self, reqs: list, now: float) -> list:
        """Fail (terminally, typed) the requests whose deadline has
        passed; returns the still-live ones."""
        live = []
        for r in reqs:
            if r.deadline_s is not None and now >= r.deadline_s:
                self._failures["deadline_misses"] += 1
                self._fail_request(r, DeadlineExceeded(
                    r.rid, now - r.submit_s, r.deadline_s - r.submit_s))
            else:
                live.append(r)
        return live

    def _expire_deadlines(self, now: float) -> None:
        """Queue-side deadline expiry (pump/flush): a request that can
        no longer make its deadline fails fast instead of wasting a
        lane.  Free until the first deadline-carrying request is
        admitted — a deadline-less service never pays the queue scan
        on its admission path."""
        if not self._has_deadlines:
            return
        for key in list(self._queues):
            q = self._queues[key]
            if not q or all(r.deadline_s is None for r in q):
                continue
            live = self._drop_expired(list(q), now)
            if len(live) != len(q):
                q.clear()
                q.extend(live)

    @staticmethod
    def _min_remaining(reqs: list, now: float) -> Optional[float]:
        rem = [r.deadline_s - now for r in reqs
               if r.deadline_s is not None]
        return min(rem) if rem else None

    # ---- warm + metrics ----------------------------------------------
    def warm(self, cfg: SimConfig, mode: str = "trace") -> None:
        """Pre-build and execute a bucket's full-batch program.

        Compiles (and runs once, on ``max_batch`` filler lanes with a
        single unstacked lane) the widest program ``cfg``'s bucket can
        dispatch, without touching request metrics — so a
        latency-sensitive caller can take the build cost up front.
        Under ``pad_policy="full"`` (the default: one width per
        bucket) a warmed bucket never builds on dispatch again; under
        ``"pow2"``/``"none"`` this warms the full-batch width only —
        partial-batch widths still compile on first use.  Warmth is
        also bounded by the program cache: warming more than
        ``cache_max_entries`` distinct buckets LRU-evicts the earliest
        ones (programs included), so size the bound to the working set
        before a warm sweep.
        """
        key = bucket_key(cfg, mode)
        sim = self.cache.get(key, cfg)
        self._filler.setdefault(key, cfg)
        self._bucket_stats.setdefault(key, {"requests": 0, "dispatches": 0,
                                            "builds": 0})
        padded = pad_configs([cfg], self._width(self.capacity), cfg)
        builds0 = run_build_count()
        if mode == "bench":
            sim.run_bench(configs=padded, warmup=False, n_real=1)
        else:
            sim.run(configs=padded, n_real=1, warmup=False)
        self._bucket_stats[key]["builds"] += run_build_count() - builds0

    def stats(self) -> dict:
        """Service-level serving metrics (the BENCH json schema).

        ``latency`` percentiles and ``mean_occupancy`` describe the
        bounded stats window (see ``stats_window``); request/dispatch
        counters are lifetime-exact.  ``mean_occupancy`` is the
        unweighted mean over dispatches (each dispatch pays its own
        program, so a half-empty batch counts half no matter how many
        requests rode it).  ``program_hit_rate`` is the fraction of
        windowed dispatches that reused an already-built compiled
        program (zero new whole-run builds) — the compiled-program
        cache metric; the ProgramCache ``hit_rate`` below it only
        counts bucket-handle reuse.
        """
        lat = np.asarray(self._latencies, dtype=np.float64)
        occ = np.asarray([d["occupancy"] for d in self._dispatches])
        hits = sum(1 for d in self._dispatches if d["builds"] == 0)
        dev = np.asarray([d["device_wait_s"] for d in self._dispatches])
        host = np.asarray([d["host_s"] for d in self._dispatches])
        walls = dev + host
        out = {
            "requests": self._next_rid,
            "completed": self._completed,
            "failed": self._failed,
            "pending": self.pending,
            "dispatches": self._dispatch_count,
            "mean_occupancy": round(float(occ.mean()), 4) if occ.size else 0.0,
            "latency_p50_s": round(float(np.percentile(lat, 50)), 6)
            if lat.size else 0.0,
            "latency_p95_s": round(float(np.percentile(lat, 95)), 6)
            if lat.size else 0.0,
            "program_hit_rate": round(hits / len(self._dispatches), 4)
            if self._dispatches else 0.0,
            # where the per-dispatch wall goes: device-wait (the mesh
            # lever moves this) vs host stack/unstack (it cannot)
            "mean_device_wait_s": round(float(dev.mean()), 6)
            if dev.size else 0.0,
            "mean_host_s": round(float(host.mean()), 6)
            if host.size else 0.0,
            "device_wait_frac": round(float(dev.sum() / walls.sum()), 4)
            if dev.size and walls.sum() > 0 else 0.0,
            "cache": self.cache.stats(),
            "max_batch": self.max_batch,
            "pad_policy": self.pad_policy,
            "devices": self.n_devices,
            "capacity": self.capacity,
            # the failure domain (PR 5): lifetime-exact counters like
            # requests/dispatches above; the windowed per-dispatch
            # view carries "retries" in each _dispatches entry
            "failures": {k: (round(v, 6) if isinstance(v, float) else v)
                         for k, v in self._failures.items()},
            "breaker_open_buckets":
                self.breaker.open_buckets(self.clock()),
        }
        out["buckets"] = {repr(k): dict(v)
                          for k, v in self._bucket_stats.items()}
        return out
