"""Mixed-workload trace replay: the service's acceptance harness.

Builds a synthetic request stream — the three grader scenario kinds
at two sizes — replays it twice (sequential per-request execution,
then through :class:`~.scheduler.FleetService`), verifies per-request
bit-parity between the two, and reports serving metrics.  Shared by
``scripts/service_smoke.py``, ``bench.py`` (the BENCH json service
entry), and the test suite (tests/test_service.py).

The two size tiers are deliberate, and their measured behavior is the
whole CPU serving story (docs/PERF.md §9):

* **grader tier** — the exact course scenarios (dense full-view,
  N=10, 700 ticks: config.SINGLE_FAILURE / MULTI_FAILURE /
  MSG_DROP_SINGLE_FAILURE).  On CPU this engine does NOT batch: the
  dense tick at N=10 is per-op-*overhead*-bound (~300 tiny XLA ops,
  ~8 us/tick) and ``vmap`` preserves the op count while adding batch
  dims to every op, so a B-lane fleet costs ~B times one lane
  (~1.0-1.2x throughput end-to-end).  The service still serves it
  correctly — and on TPU the same bucket rides the batch-native
  megakernels instead of vmap.
* **scale tier** — the same three scenario kinds in the bounded
  partial-view overlay family (fail / churn / drop10, the
  bench_overlay shapes at replay size).  This engine is where
  continuous batching pays on CPU: ~3x at B=8 (PERF §8/§9), and it
  dominates the stream's node-tick volume, so the replayed stream
  sustains >= 2x sequential throughput overall.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from ..config import (MSG_DROP_SINGLE_FAILURE, MULTI_FAILURE,
                      SINGLE_FAILURE, SimConfig)
from .scheduler import FleetService

#: overlay state/metric fields compared for parity (live_uncovered is
#: excluded by contract: the fleet reports the kernels' -1 sentinel,
#: core/fleet.py / tests/test_fleet.py)
_OV_STATE = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
             "send_flags", "joinreq", "joinrep")
_OV_METRICS = ("in_group", "view_slots", "adds", "removals",
               "false_removals", "victim_slots", "sent", "recv")
_DENSE_STATE = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "joinreq", "joinrep")


@dataclass(frozen=True)
class Template:
    """One (scenario kind, size tier) request template."""

    name: str
    cfg: SimConfig
    mode: str = "trace"


def grader_templates() -> list[Template]:
    """The grader tier: the three exact course scenarios (dense N=10)."""
    return [Template("dense-single", SINGLE_FAILURE),
            Template("dense-multi", MULTI_FAILURE),
            Template("dense-drop10", MSG_DROP_SINGLE_FAILURE)]


def overlay_templates(n: int = 512, ticks: int = 96) -> list[Template]:
    """The scale tier: the same scenario kinds, overlay family.

    Mirrors ``bench_overlay``'s fail/churn/drop shapes at replay size
    (churn keeps the ramp inside the pre-churn window; drop keeps it
    before the tick-50 window opening, like the reference's msgdrop
    scenario).
    """
    # ramps scale with the tick budget: the whole join ramp must land
    # before the churn window opens (ticks/4) resp. before the fail
    # tick and the tick-50 drop-window opening
    ramp_fail = min(40, max(1, ticks // 2 - 8))
    ramp_churn = max(1, ticks // 4 - 4)
    fail = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                     drop_msg=False, seed=0, total_ticks=ticks,
                     fail_tick=ticks // 2, step_rate=ramp_fail / n)
    churn = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                      drop_msg=False, seed=0, total_ticks=ticks,
                      churn_rate=0.2, rejoin_after=40,
                      step_rate=ramp_churn / n)
    drop = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                     drop_msg=True, msg_drop_prob=0.1, seed=0,
                     total_ticks=ticks, fail_tick=ticks // 2,
                     step_rate=ramp_fail / n)
    return [Template("overlay-fail", fail), Template("overlay-churn", churn),
            Template("overlay-drop10", drop)]


def build_trace(templates: list[Template],
                seeds_per_template: int) -> list[tuple[Template, int]]:
    """Seed-major interleaving: every template at seed k arrives before
    any template at seed k+1, so buckets fill concurrently — the shape
    mix a real request stream would present, not sorted batches."""
    return [(tpl, 1000 + s) for s in range(seeds_per_template)
            for tpl in templates]


def _solo_run(tpl: Template, seed: int):
    """Direct single-simulation execution of one request — the SAME
    implementation the degradation fallback uses
    (service/resilience.py ``solo_execute``), so the parity reference
    and the fallback cannot drift apart."""
    from .resilience import solo_execute
    return solo_execute(tpl.cfg.replace(seed=seed), tpl.mode)


def run_sequential(trace) -> tuple[list, float]:
    """The baseline leg: every request alone, in arrival order.

    Compiled runs are process-cached per shape (core/tick.make_run,
    models/overlay.make_overlay_run), so after the caller's warmup
    pass this leg pays no compilation — it is the honest "no serving
    layer" alternative, not a strawman.
    """
    t0 = time.perf_counter()
    out = [_solo_run(tpl, seed) for tpl, seed in trace]
    return out, time.perf_counter() - t0


def run_service(trace, max_batch: int = 8,
                service: FleetService | None = None,
                pipeline: bool | None = None,
                pipeline_depth: int | None = None
                ) -> tuple[list, FleetService, float]:
    """The serving leg: submit the stream, drain, collect results."""
    svc = service if service is not None else FleetService(
        max_batch=max_batch, pipeline=pipeline,
        pipeline_depth=pipeline_depth)
    t0 = time.perf_counter()
    handles = [svc.submit(tpl.cfg, seed=seed, mode=tpl.mode)
               for tpl, seed in trace]
    svc.drain()
    results = [h.result() for h in handles]
    return results, svc, time.perf_counter() - t0


def warm(trace, service: FleetService) -> None:
    """Compile both legs' programs before timing (one pass per
    distinct template): the comparison measures serving, not
    compilation."""
    done = set()
    for tpl, _ in trace:
        if tpl.name in done:
            continue
        done.add(tpl.name)
        _solo_run(tpl, 1)
        service.warm(tpl.cfg, tpl.mode)


def _mismatch(tpl: Template, ref, got) -> str | None:
    """First differing field between a solo result and a service lane
    (None: bit-identical)."""
    if tpl.cfg.model == "overlay":
        for f in _OV_STATE:
            if not np.array_equal(np.asarray(getattr(ref.final_state, f)),
                                  np.asarray(getattr(got.final_state, f))):
                return f"final_state.{f}"
        for f in _OV_METRICS:
            if not np.array_equal(np.asarray(getattr(ref.metrics, f)),
                                  np.asarray(getattr(got.metrics, f))):
                return f"metrics.{f}"
        return None
    for f in ("added", "removed", "sent", "recv"):
        a, b = getattr(ref, f), getattr(got, f)
        if (a is None) != (b is None) or \
                (a is not None and not np.array_equal(a, b)):
            return f
    for f in _DENSE_STATE:
        if not np.array_equal(np.asarray(getattr(ref.final_state, f)),
                              np.asarray(getattr(got.final_state, f))):
            return f"final_state.{f}"
    return None


def result_digest(res) -> str:
    """Stable content hash of ONE request's result — exactly the
    parity fields ``_mismatch`` compares, so two results with equal
    digests are bit-identical by the replay harness's own standard.

    This is what the write-ahead journal records per terminal request
    (store/journal.py ``outcome``): a run killed after a request
    completed can still prove that request's bit-parity against an
    uninterrupted baseline without the result surviving the death.
    Pure host numpy (registered under the purity lint's host-staging
    rule).
    """
    import hashlib
    h = hashlib.sha256()

    def _fold(tag: str, a) -> None:
        h.update(tag.encode())
        if a is None:
            h.update(b"<none>")
            return
        a = np.ascontiguousarray(np.asarray(a))
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())

    if hasattr(res, "metrics"):           # overlay result
        for f in _OV_STATE:
            _fold(f"state.{f}", getattr(res.final_state, f))
        for f in _OV_METRICS:
            _fold(f"metrics.{f}", getattr(res.metrics, f))
    else:                                 # dense result (trace/bench)
        for f in ("added", "removed", "sent", "recv"):
            _fold(f, getattr(res, f))
        for f in _DENSE_STATE:
            _fold(f"state.{f}", getattr(res.final_state, f))
    return h.hexdigest()[:16]


def verify_parity(trace, seq_results, svc_results) -> list[str]:
    """Per-request bit-parity of the two legs; returns mismatches."""
    bad = []
    for (tpl, seed), ref, got in zip(trace, seq_results, svc_results):
        field = _mismatch(tpl, ref, got)
        if field is not None:
            bad.append(f"{tpl.name} seed={seed}: {field}")
    return bad


def node_ticks(trace) -> int:
    return sum(t.cfg.n * t.cfg.total_ticks for t, _ in trace)


def replay(templates: list[Template], seeds_per_template: int,
           max_batch: int = 8, check_parity: bool = True,
           mesh=None, sequential=None, return_legs: bool = False,
           pipeline: bool | None = None,
           pipeline_depth: int | None = None):
    """Full A/B replay; returns the service-metrics dict for BENCH.

    Raises on any per-request parity mismatch — a serving layer that
    changes results has no throughput to report.

    ``mesh`` serves the stream from a lane mesh — 1-D lanes or 2-D
    lanes x peers (parallel/fleet_mesh.py): ``max_batch`` is then the
    PER-LANE-DEVICE width, so pass ``max_batch = total_lanes //
    n_lanes`` to compare decompositions at equal total lane width
    (the PERF §10 curve); on a 2-D mesh the peer axis shards each
    simulation's peer tables instead of multiplying capacity.

    The sequential baseline of one trace is the same however the
    service side is configured, so a caller comparing several service
    configurations (device counts, batch widths) can run it once:
    ``return_legs=True`` additionally returns ``(seq_results,
    seq_wall)``, and ``sequential=`` feeds that pair back in place of
    a fresh baseline run — parity is still verified per request
    against it.
    """
    trace = build_trace(templates, seeds_per_template)
    svc = FleetService(max_batch=max_batch, mesh=mesh,
                       pipeline=pipeline,
                       pipeline_depth=pipeline_depth)
    warm(trace, svc)
    if sequential is None:
        seq_results, seq_wall = run_sequential(trace)
    else:
        seq_results, seq_wall = sequential
        if len(seq_results) != len(trace):
            raise ValueError(
                f"sequential= leg has {len(seq_results)} results but "
                f"the trace has {len(trace)} requests; both replays "
                "must use the same templates and seeds_per_template")
    svc_results, svc, svc_wall = run_service(trace, service=svc)
    # the clean-path harness must stay loud about engine failures: the
    # resilient scheduler would otherwise convert a broken fleet path
    # into solo-run fallbacks that pass parity trivially (solo IS the
    # reference) — a fault-free replay that degrades anything is a bug
    fail_stats = svc.stats()
    if fail_stats["failed"] or fail_stats["failures"]["degraded_requests"]:
        raise RuntimeError(
            f"fault-free replay had {fail_stats['failed']} failed and "
            f"{fail_stats['failures']['degraded_requests']} degraded "
            f"requests (retries="
            f"{fail_stats['failures']['retries']}); the fleet dispatch "
            "path is broken — its errors are on the request handles")
    if check_parity:
        bad = verify_parity(trace, seq_results, svc_results)
        if bad:
            raise RuntimeError(
                f"service results diverged from solo runs ({len(bad)}): "
                + "; ".join(bad[:5]))
    stats = svc.stats()
    nt = node_ticks(trace)
    # builds attributable to service buckets (warm + dispatch); the
    # cache's own ``builds`` is a process-wide delta that also counts
    # the sequential leg's solo compilations
    per_bucket_builds = [b["builds"] for b in stats["buckets"].values()]
    metrics = {
        "requests": len(trace),
        "distinct_templates": len(templates),
        "devices": stats["devices"],
        "lanes": stats["lanes"],
        "peers": stats["peers"],
        "capacity": stats["capacity"],
        "sequential_wall_s": round(seq_wall, 3),
        "service_wall_s": round(svc_wall, 3),
        "speedup_vs_sequential": round(seq_wall / svc_wall, 2),
        "aggregate_node_ticks_per_s": round(nt / svc_wall, 1),
        "sequential_node_ticks_per_s": round(nt / seq_wall, 1),
        "latency_p50_s": stats["latency_p50_s"],
        "latency_p95_s": stats["latency_p95_s"],
        "mean_occupancy": stats["mean_occupancy"],
        "pipeline": stats["pipeline"],
        "pipeline_depth": stats["pipeline_depth"],
        "ring_stalls": stats["ring_stalls"],
        "mean_pack_s": stats["mean_pack_s"],
        "mean_device_wait_s": stats["mean_device_wait_s"],
        "mean_fetch_s": stats["mean_fetch_s"],
        "mean_host_s": stats["mean_host_s"],
        "device_wait_frac": stats["device_wait_frac"],
        # compiled-program reuse per dispatch (zero new builds) — the
        # honest cache metric; ProgramCache.hit_rate only counts
        # bucket-handle reuse
        "cache_hit_rate": stats["program_hit_rate"],
        "buckets": stats["cache"]["buckets"],
        "service_builds": sum(per_bucket_builds),
        "max_builds_per_bucket": max(per_bucket_builds, default=0),
        "dispatches": stats["dispatches"],
        "parity_checked": bool(check_parity),
    }
    if return_legs:
        return metrics, (seq_results, seq_wall)
    return metrics


def chaos_replay(templates: list[Template], seeds_per_template: int,
                 max_batch: int = 8, mesh=None, fault_seed: int = 0,
                 fault_rate: float = 0.12, device_loss_at="mid",
                 max_retries: int = 4, backoff_base_s: float = 0.01,
                 sequential=None, return_legs: bool = False,
                 pipeline: bool | None = None,
                 pipeline_depth: int | None = None):
    """The chaos acceptance harness: the mixed replay under a SEEDED
    fault schedule (service/faults.py) plus one mid-replay device
    loss, with the gate enforced in-line:

    * **100% completion, 0 stranded handles** — every submitted
      request reaches a terminal state, and every terminal state is a
      result (completed or degraded-to-solo); any failed or pending
      handle raises.
    * **bit-parity for every non-degraded request** against the
      sequential solo leg (degraded requests ARE solo runs, so they
      are checked too — a degraded mismatch raises just the same).
    * **replayability** — the returned ``fault_events`` /
      ``schedule_digest`` / ``outcomes`` are pure functions of
      ``(templates, seeds_per_template, max_batch, mesh, fault_seed,
      fault_rate, device_loss_at)``: two runs with the same arguments
      produce identical fault sequences and identical per-request
      outcomes.  Nothing may depend on wall time: ``max_wait_s`` stays
      None (dispatch order is a pure function of submit order) and the
      circuit-breaker cooldown is infinite (an opened bucket stays
      deterministically quarantined rather than half-open-probing on
      elapsed wall time).

    ``device_loss_at="mid"`` schedules the loss at roughly the middle
    dispatch; pass an attempt index to pin it, or None for no loss.
    ``sequential=``/``return_legs=`` share one solo baseline across
    several chaos configurations, exactly like :func:`replay`.
    """
    from .faults import FaultInjector
    from .resilience import BreakerPolicy, RetryPolicy
    trace = build_trace(templates, seeds_per_template)
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    # capacity scales with the LANE axis only (2-D meshes spend the
    # peer axis on n-sharding, not batch width)
    if mesh is not None:
        from ..parallel.fleet_mesh import mesh_axis_sizes
        n_lanes = mesh_axis_sizes(mesh)[0]
    else:
        n_lanes = 1
    if device_loss_at == "mid":
        # roughly the middle fault-free dispatch of the stream
        dispatches = max(1, len(trace) // max(1, max_batch * n_lanes))
        device_loss_at = max(2, dispatches // 2)
    injector = FaultInjector(seed=fault_seed, fault_rate=fault_rate,
                             device_loss_at=device_loss_at)
    svc = FleetService(
        max_batch=max_batch, mesh=mesh, injector=injector,
        retry=RetryPolicy(max_retries=max_retries,
                          backoff_base_s=backoff_base_s,
                          seed=fault_seed),
        # determinism requires every scheduling decision to be a pure
        # function of the seeded arguments: max_wait_s stays None (no
        # time-based flushes) and the breaker cooldown is infinite —
        # a bucket the fault schedule manages to open stays
        # deterministically quarantined (its requests degrade to solo,
        # which still completes and parity-checks) instead of
        # half-open-probing on real elapsed wall time.  Pipelining
        # (the default) keeps determinism: launches, resolves, and
        # retries all happen at fixed points of the submit/flush
        # sequence, so attempt indices — and with them the fault
        # schedule — are still a pure function of submit order.
        breaker=BreakerPolicy(reset_after_s=float("inf")),
        pipeline=pipeline, pipeline_depth=pipeline_depth)
    warm(trace, svc)
    if sequential is None:
        seq_results, seq_wall = run_sequential(trace)
    else:
        seq_results, seq_wall = sequential
        if len(seq_results) != len(trace):
            raise ValueError(
                f"sequential= leg has {len(seq_results)} results but "
                f"the trace has {len(trace)} requests")
    t0 = time.perf_counter()
    handles = [svc.submit(tpl.cfg, seed=seed, mode=tpl.mode)
               for tpl, seed in trace]
    svc.drain()
    svc_wall = time.perf_counter() - t0

    stranded = [h.request.rid for h in handles if not h.done]
    failed = [h.request.rid for h in handles if h.failed]
    if stranded or failed:
        errs = "; ".join(
            f"rid {h.request.rid}: {h.exception()!r}"
            for h in handles if h.failed)[:500]
        raise RuntimeError(
            f"chaos replay left {len(stranded)} stranded and "
            f"{len(failed)} failed handles of {len(handles)} "
            f"(seed={fault_seed}): {errs}")
    svc_results = [h.result() for h in handles]
    degraded = [h.request.rid for h in handles
                if h.status == "degraded"]
    bad = verify_parity(trace, seq_results, svc_results)
    # degraded requests are served by the parity reference itself
    # (solo runs), so ANY mismatch — degraded or not — is a failure
    if bad:
        raise RuntimeError(
            f"chaos replay diverged from solo runs ({len(bad)}): "
            + "; ".join(bad[:5]))
    stats = svc.stats()
    outcomes = [(h.request.rid, h.status, h.metrics.retries)
                for h in handles]
    import hashlib
    outcome_digest = hashlib.sha256(
        repr(outcomes).encode()).hexdigest()[:16]
    metrics = {
        "requests": len(trace),
        "completed": len(svc_results),
        "stranded": 0,
        "failed": 0,
        "completion_rate": 1.0,
        "degraded_requests": len(degraded),
        "parity_checked": True,
        "fault_seed": fault_seed,
        "fault_rate": fault_rate,
        "device_loss_at": device_loss_at,
        "faults": injector.summary(),
        "fault_events": list(injector.events),
        "schedule_digest": injector.schedule_digest(),
        "outcome_digest": outcome_digest,
        "outcomes": outcomes,
        "failures": stats["failures"],
        "devices_start": n_dev,
        "devices_end": stats["devices"],
        "sequential_wall_s": round(seq_wall, 3),
        "service_wall_s": round(svc_wall, 3),
        "speedup_vs_sequential": round(seq_wall / svc_wall, 2),
        "latency_p50_s": stats["latency_p50_s"],
        "latency_p95_s": stats["latency_p95_s"],
        "mean_occupancy": stats["mean_occupancy"],
        "dispatches": stats["dispatches"],
        "pipeline": stats["pipeline"],
        "pipeline_depth": stats["pipeline_depth"],
        "ring_stalls": stats["ring_stalls"],
        "breaker_open_buckets": stats["breaker_open_buckets"],
    }
    if return_legs:
        return metrics, (seq_results, seq_wall)
    return metrics


def elastic_replay(templates: list[Template], seeds_per_template: int,
                   max_batch: int = 4, mesh=None,
                   checkpoint_every: int = 32, fault_seed: int = 0,
                   fault_rate: float = 0.0, device_loss_at="mid",
                   device_return_at="after", max_retries: int = 4,
                   backoff_base_s: float = 0.01, sequential=None,
                   return_legs: bool = False,
                   pipeline: bool | None = None,
                   pipeline_depth: int | None = None):
    """The elastic acceptance harness (PR 8): the mixed replay served
    as RESUMABLE LEGS (``checkpoint_every`` segment budget) under one
    seeded device loss AND one device return, with the gate enforced
    in-line:

    * **100% completion, 0 stranded handles** — like the chaos gate;
    * **zero lanes restarted from tick 0** — every lane interrupted
      after its first checkpoint resumes from that checkpoint (the
      scheduler's ``restarted_lanes`` counter must be 0), and the
      harness additionally requires that checkpoints, resume
      dispatches, and — when a mesh rides — cross-rebuild lane
      migrations actually happened (a run too small to exercise them
      raises rather than passing vacuously);
    * **shrink -> grow round trip** — the device loss shrinks the
      mesh, the device return grows it back; the service must end at
      its starting device count with at least one ``mesh_grows``;
    * **bit-parity for every request** against the sequential solo
      leg (degraded requests are solo-RESUMED from their checkpoint —
      still exact);
    * **replayability** — fault schedule + per-request outcomes
      (status, retries, legs) are pure functions of the seeded
      arguments, digest-comparable across two runs.

    ``device_loss_at="mid"`` places the loss mid-stream by attempt
    index; ``device_return_at="after"`` a few attempts later (pass
    ints to pin either).  ``sequential=``/``return_legs=`` share one
    solo baseline across configurations, like :func:`replay`.
    """
    from .faults import FaultInjector
    from .resilience import BreakerPolicy, RetryPolicy
    trace = build_trace(templates, seeds_per_template)
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    # dispatch capacity scales with the LANE axis only; the peer axis
    # of a 2-D mesh shards n within each lane (and is what the
    # axis-aware shrink drops first — the peer-shard elasticity path)
    if mesh is not None:
        from ..parallel.fleet_mesh import mesh_axis_sizes
        n_lanes = mesh_axis_sizes(mesh)[0]
    else:
        n_lanes = 1
    cap = max(1, max_batch * n_lanes)
    base_dispatches = max(1, -(-len(trace) // cap))
    if device_loss_at == "mid":
        # with legs the attempt stream is ~2-4x the batch count; the
        # base count lands the loss inside the leg stream's first half,
        # when checkpoints already exist
        device_loss_at = max(2, base_dispatches)
    if device_return_at == "after":
        device_return_at = device_loss_at + max(2, base_dispatches // 2)
    injector = FaultInjector(seed=fault_seed, fault_rate=fault_rate,
                             device_loss_at=device_loss_at,
                             device_return_at=device_return_at)
    svc = FleetService(
        max_batch=max_batch, mesh=mesh, injector=injector,
        retry=RetryPolicy(max_retries=max_retries,
                          backoff_base_s=backoff_base_s,
                          seed=fault_seed),
        # same determinism pins as chaos_replay: no time-based flushes,
        # an opened bucket stays deterministically quarantined
        breaker=BreakerPolicy(reset_after_s=float("inf")),
        checkpoint_every=checkpoint_every, pipeline=pipeline,
        pipeline_depth=pipeline_depth)
    warm(trace, svc)
    if sequential is None:
        seq_results, seq_wall = run_sequential(trace)
    else:
        seq_results, seq_wall = sequential
        if len(seq_results) != len(trace):
            raise ValueError(
                f"sequential= leg has {len(seq_results)} results but "
                f"the trace has {len(trace)} requests")
    t0 = time.perf_counter()
    handles = [svc.submit(tpl.cfg, seed=seed, mode=tpl.mode)
               for tpl, seed in trace]
    svc.drain()
    svc_wall = time.perf_counter() - t0

    stranded = [h.request.rid for h in handles if not h.done]
    failed = [h.request.rid for h in handles if h.failed]
    if stranded or failed:
        errs = "; ".join(
            f"rid {h.request.rid}: {h.exception()!r}"
            for h in handles if h.failed)[:500]
        raise RuntimeError(
            f"elastic replay left {len(stranded)} stranded and "
            f"{len(failed)} failed handles of {len(handles)} "
            f"(seed={fault_seed}): {errs}")
    svc_results = [h.result() for h in handles]
    bad = verify_parity(trace, seq_results, svc_results)
    if bad:
        raise RuntimeError(
            f"elastic replay diverged from solo runs ({len(bad)}): "
            + "; ".join(bad[:5]))
    stats = svc.stats()
    summary = injector.summary()
    if summary["device_loss"] < 1 or summary["device_return"] < 1:
        raise RuntimeError(
            f"elastic replay injected {summary['device_loss']} device "
            f"losses / {summary['device_return']} returns; the gate "
            "needs >= 1 of each — the attempt stream never reached "
            f"indices {device_loss_at}/{device_return_at} (stream too "
            "small for the leg budget?)")
    el = stats["elastic"]
    if el["restarted_lanes"] != 0:
        raise RuntimeError(
            f"elastic replay restarted {el['restarted_lanes']} "
            "checkpointed lane(s) from tick 0; interrupted lanes must "
            "resume from their last checkpoint")
    if el["checkpoints_taken"] < 1 or el["resume_dispatches"] < 1:
        raise RuntimeError(
            f"elastic replay took {el['checkpoints_taken']} "
            f"checkpoints / {el['resume_dispatches']} resume "
            "dispatches; the gate is vacuous without resumable legs — "
            "lower checkpoint_every or lengthen the configs")
    if mesh is not None:
        if el["lanes_migrated"] < 1:
            raise RuntimeError(
                "elastic replay migrated no lanes across the mesh "
                "rebuild; the loss/return events missed every "
                "checkpointed batch")
        if el["mesh_grows"] < 1 or stats["devices"] != n_dev:
            raise RuntimeError(
                f"elastic replay ended at {stats['devices']} devices "
                f"(started {n_dev}, grows={el['mesh_grows']}); the "
                "returned device was never reclaimed")
    degraded = [h.request.rid for h in handles
                if h.status == "degraded"]
    outcomes = [(h.request.rid, h.status, h.metrics.retries,
                 h.metrics.legs) for h in handles]
    import hashlib
    outcome_digest = hashlib.sha256(
        repr(outcomes).encode()).hexdigest()[:16]
    metrics = {
        "requests": len(trace),
        "completed": len(svc_results),
        "stranded": 0,
        "failed": 0,
        "completion_rate": 1.0,
        "degraded_requests": len(degraded),
        "parity_checked": True,
        "fault_seed": fault_seed,
        "fault_rate": fault_rate,
        "checkpoint_every": checkpoint_every,
        "device_loss_at": device_loss_at,
        "device_return_at": device_return_at,
        "faults": summary,
        "fault_events": list(injector.events),
        "schedule_digest": injector.schedule_digest(),
        "outcome_digest": outcome_digest,
        "outcomes": outcomes,
        "elastic": el,
        "restarted_from_zero": el["restarted_lanes"],
        "mean_legs": round(sum(o[3] for o in outcomes)
                           / max(len(outcomes), 1), 2),
        "cache_rekey_hits": stats["cache"]["rekey_hits"],
        "failures": stats["failures"],
        "devices_start": n_dev,
        "devices_end": stats["devices"],
        "lanes_end": stats["lanes"],
        "peers_end": stats["peers"],
        "sequential_wall_s": round(seq_wall, 3),
        "service_wall_s": round(svc_wall, 3),
        "speedup_vs_sequential": round(seq_wall / svc_wall, 2),
        "latency_p50_s": stats["latency_p50_s"],
        "latency_p95_s": stats["latency_p95_s"],
        "mean_occupancy": stats["mean_occupancy"],
        "dispatches": stats["dispatches"],
        "pipeline": stats["pipeline"],
        "pipeline_depth": stats["pipeline_depth"],
        "ring_stalls": stats["ring_stalls"],
    }
    if return_legs:
        return metrics, (seq_results, seq_wall)
    return metrics
