"""Seeded, deterministic fault injection for the serving layer.

The paper's subject is surviving failure — peers must tolerate message
drops and node crashes with bounded false positives — and the grader
applies that discipline to the *protocol* (the drop10 scenario).  This
module applies the same discipline to the layer that serves it: every
boundary the scheduler crosses on a dispatch (compile, device
dispatch, result unstacking, the mesh itself) can be made to fail on
purpose, from a seed, so chaos runs are replayable regression tests
rather than flakes.

Determinism is the whole design.  A fault decision is a pure function
of ``(seed, attempt_index)`` — drawn from a fresh
``numpy.random.default_rng((seed, idx))``, never from mutable RNG
state — so the i-th dispatch attempt of a replay sees the same fault
no matter what happened around it, and two runs of the same trace with
the same seed produce the identical fault sequence AND the identical
per-request outcomes (pinned by tests/test_resilience.py and the
acceptance gate in service/replay.py ``chaos_replay``).  The service
is single-threaded and its dispatch order is a pure function of the
submit order (no time-based flushes in chaos runs), which closes the
loop.

Fault taxonomy (docs/SERVING.md "Failure model"):

========== =========================================================
kind       injected where / what it simulates
========== =========================================================
compile    raised at the program-build boundary, before the bucket's
           FleetSimulation is even looked up — a failed XLA compile
           or a poisoned program cache entry
dispatch   raised between program lookup and execution — a device
           runtime error (the classic transient)
latency    the dispatch completes, then stalls for a deterministic
           extra wait — a slow device / contended host, exercising
           deadline accounting without failing anything
poison     one lane of the finished FleetResult is corrupted
           (message counters forced negative) — a bad result that
           only *validation* can catch (service/resilience.py
           ``validate_lane``)
device_loss raised once, at ``device_loss_at`` — a device dropping
           out of the lane mesh; the scheduler shrinks the mesh and
           rebuilds (parallel/fleet_mesh.py ``shrink_mesh``)
device_return fires once, at ``device_return_at`` — a lost device
           coming BACK (PR 8 elastic serving).  Not a failure: the
           scheduler grows the mesh (``grow_mesh``) and re-keys the
           program cache before launching the attempt, then proceeds
           normally.  Recorded in :attr:`events` like every fault,
           so grow events replay digest-for-digest.
========== =========================================================

The injector never touches engine code: it is consulted by
``FleetService._serve_batch`` at each boundary, which keeps the fault
plane a pure serving-layer concern (and keeps solo runs — the
degradation ladder's bottom rung and the parity reference — outside
its reach by construction).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: the injectable fault kinds, in the order the seeded draw indexes
#: them (stable order = stable schedules across code motion)
FAULT_KINDS = ("compile", "dispatch", "latency", "poison")


class InjectedFault(RuntimeError):
    """Base of every fault the injector raises (never of the errors
    the resilience layer raises on *detection* — those live in
    service/resilience.py)."""

    kind = "injected"

    def __init__(self, idx: int, detail: str = ""):
        self.idx = idx
        super().__init__(
            f"injected {self.kind} fault at dispatch attempt {idx}"
            + (f": {detail}" if detail else ""))


class InjectedCompileFailure(InjectedFault):
    kind = "compile"


class InjectedDispatchFailure(InjectedFault):
    kind = "dispatch"


class InjectedDeviceLoss(InjectedFault):
    kind = "device_loss"


class FaultInjector:
    """Deterministic fault schedule over dispatch-attempt indices.

    ``fault_rate`` is the per-attempt probability of injecting one of
    ``kinds`` (uniformly); ``device_loss_at`` names ONE attempt index
    that additionally raises a device loss (it wins over the seeded
    draw at that index).  ``schedule`` pins explicit
    ``{attempt_index: kind}`` decisions instead of the seeded draw —
    the unit-test mode, equally deterministic.

    The injector records every injected fault in :attr:`events`
    (``(idx, kind)`` in injection order); :meth:`summary` counts them
    per kind and :meth:`schedule_digest` folds events into a short
    stable hash, which the chaos harness compares across two runs of
    the same seed to prove replayability.
    """

    def __init__(self, seed: int = 0, fault_rate: float = 0.0,
                 kinds=FAULT_KINDS, latency_s: float = 0.05,
                 device_loss_at: Optional[int] = None,
                 device_return_at: Optional[int] = None,
                 schedule: Optional[dict] = None):
        if not 0.0 <= fault_rate <= 1.0:
            raise ValueError(f"fault_rate must be in [0, 1], got "
                             f"{fault_rate}")
        unknown = set(kinds) - set(FAULT_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds {sorted(unknown)}; "
                             f"expected a subset of {FAULT_KINDS}")
        if schedule is not None:
            bad = set(schedule.values()) - set(FAULT_KINDS) \
                - {"device_loss", "device_return"}
            if bad:
                raise ValueError(
                    f"unknown fault kinds in schedule {sorted(bad)}; "
                    f"expected {FAULT_KINDS} + ('device_loss', "
                    "'device_return')")
        self.seed = int(seed)
        self.fault_rate = float(fault_rate)
        self.kinds = tuple(kinds)
        self.base_latency_s = float(latency_s)
        self.device_loss_at = device_loss_at
        #: ONE attempt index at which a lost device returns (the grow
        #: half of the elasticity ladder).  Like ``device_loss_at`` it
        #: wins over the seeded draw at its index — and losing wins
        #: over returning when both name the same index (a return
        #: cannot shadow the loss it answers)
        self.device_return_at = device_return_at
        self.schedule = dict(schedule) if schedule is not None else None
        self.events: list[tuple[int, str]] = []

    # ---- the deterministic draw -------------------------------------
    def _kind(self, idx: int) -> Optional[str]:
        if self.device_loss_at is not None and idx == self.device_loss_at:
            return "device_loss"
        if self.device_return_at is not None \
                and idx == self.device_return_at:
            return "device_return"
        if self.schedule is not None:
            return self.schedule.get(idx)
        if self.fault_rate <= 0.0 or not self.kinds:
            return None
        rng = np.random.default_rng((self.seed, idx))
        if rng.random() >= self.fault_rate:
            return None
        return self.kinds[int(rng.integers(len(self.kinds)))]

    def plan(self, idx: int) -> Optional[str]:
        """The fault (or None) for dispatch attempt ``idx``; injected
        faults are appended to :attr:`events`."""
        kind = self._kind(idx)
        if kind is not None:
            self.events.append((int(idx), kind))
        return kind

    def latency_s(self, idx: int) -> float:
        """Deterministic injected-latency duration for attempt ``idx``
        (0.5x-1.5x the base, drawn from the same seed plane)."""
        rng = np.random.default_rng((self.seed, idx, 1))
        return self.base_latency_s * (0.5 + float(rng.random()))

    def poison(self, fleet, idx: int) -> int:
        """Corrupt one lane of a finished FleetResult (deterministic
        lane choice): its message counters are forced negative — an
        impossible value the scheduler's lane validation must catch
        (service/resilience.py ``validate_lane``).  Returns the
        poisoned lane index.

        The corrupted array is REPLACED on the lane, not mutated in
        place: overlay metrics cross to host as read-only numpy views
        of device arrays (writing into them raises instead of
        poisoning — pinned by
        tests/test_resilience.py::test_poison_overlay_lane_detected)."""
        rng = np.random.default_rng((self.seed, idx, 2))
        i = int(rng.integers(len(fleet.lanes)))
        lane = fleet.lanes[i]
        if hasattr(lane, "chunks"):     # a LaneCheckpoint (elastic leg)
            # corrupt the leg's OWN chunk only: the retry rebuilds
            # from the PREVIOUS checkpoint, whose chunk list this
            # replacement never touches (core/fleet.py
            # _advance_checkpoints copies the list per leg)
            ch = lane.chunks[-1]
            if hasattr(ch, "sent"):                     # overlay metrics
                lane.chunks[-1] = ch.replace(
                    sent=np.full_like(np.asarray(ch.sent), -1))
            else:                                       # dense trace tuple
                a, r, s, rc = ch
                lane.chunks[-1] = (a, r,
                                   np.full_like(np.asarray(s), -1), rc)
        elif hasattr(lane, "metrics"):                  # overlay
            sent = np.asarray(lane.metrics.sent)
            lane.metrics = lane.metrics.replace(
                sent=np.full_like(sent, -1))
        else:                                           # dense SimResult
            lane.sent = np.full_like(np.asarray(lane.sent), -1)
        return i

    # ---- provenance --------------------------------------------------
    def summary(self) -> dict:
        out = {k: 0 for k in FAULT_KINDS
               + ("device_loss", "device_return")}
        for _, kind in self.events:
            out[kind] += 1
        out["total"] = len(self.events)
        return out

    def schedule_digest(self) -> str:
        """Stable short hash of the injected fault sequence — equal
        across two runs iff the same faults fired at the same attempt
        indices."""
        import hashlib
        return hashlib.sha256(repr(self.events).encode()).hexdigest()[:16]
