"""SLO policy for the serving layer: priority classes, per-class
deadlines, and deadline-aware batch formation.

The scheduler's flush policies through PR 6 were *throughput* policies:
dispatch when a bucket is full, when its oldest request has waited
``max_wait_s``, or when a caller forces it.  Under an open-loop request
stream (service/traffic.py) that is not enough — a latency-sensitive
request stuck in a slowly-filling bucket will blow its deadline waiting
for lanes that may never arrive.  This module adds the *latency* side:

* **priority classes** — a request carries a class name
  (``submit(..., priority=)``); each class has a default relative
  deadline, so callers opt into an SLO by naming a class instead of
  hand-picking budgets.  Classes also carry the traffic generator's
  mix weights, so one object describes both what load looks like and
  what it is owed.
* **deadline-aware early flush** — the scheduler flushes a PARTIAL
  bucket early when its tightest deadline minus the bucket's estimated
  dispatch wall says the batch must go *now* to make it
  (``FleetService._should_flush_early``).  Both inputs already exist:
  deadlines ride the requests (PR 5) and the per-bucket wall comes
  from the PR-6 pack/execute/fetch decomposition, folded into an EWMA
  per bucket (seeded by ``warm()``).  The trade is explicit: occupancy
  is sacrificed exactly when a deadline is at stake, never otherwise.
* **per-tenant admission quotas** — ``FleetService(tenant_quota=N)``
  bounds the *queued* requests any one tenant may hold, layered on the
  global ``max_queue_depth``: one hot tenant saturating the queue
  sheds typed (:class:`~.resilience.TenantQuotaExceeded`, a
  :class:`~.resilience.ShedRejection`) instead of starving everyone
  else's SLOs.  Queued work is never dropped — admission is refused,
  with the tenant named.

Determinism note (the same discipline as the chaos plane): the early
flush decision compares a *virtual* deadline margin against a
*measured* wall estimate.  For seed-replayable runs (the smoke load
gate, the chaos-under-load regression test) pin
``assumed_dispatch_wall_s`` so the decision is a pure function of the
schedule; leave it None in production/bench runs to use the measured
per-bucket EWMA.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Mapping, Optional


@dataclass(frozen=True)
class ClassPolicy:
    """One priority class: its default relative deadline (None: no
    deadline — the class is throughput-only, and STAYS deadline-less
    even on a service with ``default_deadline_s`` set: an SLO policy
    owns the deadline decision) and its weight in the traffic
    generator's class mix."""

    deadline_s: Optional[float] = None
    weight: float = 1.0

    def __post_init__(self):
        if self.deadline_s is not None and self.deadline_s <= 0.0:
            raise ValueError(f"deadline_s must be > 0 or None, got "
                             f"{self.deadline_s}")
        if self.weight < 0.0:
            raise ValueError(f"weight must be >= 0, got {self.weight}")


@dataclass(frozen=True)
class SLOPolicy:
    """Priority classes + the early-flush rule.

    A partial bucket is flushed early when its tightest remaining
    deadline margin drops to
    ``est_wall * safety_factor + margin_s`` — ``est_wall`` being the
    bucket's EWMA dispatch wall (or ``assumed_dispatch_wall_s`` when
    pinned for deterministic replays).  ``early_flush=False`` keeps
    the classes and deadlines but disables the early dispatch — the
    A/B leg the load bench compares miss rates against.
    """

    classes: Mapping[str, ClassPolicy] = field(
        default_factory=lambda: {"standard": ClassPolicy()})
    default_class: str = "standard"
    early_flush: bool = True
    #: deadline-aware DISPATCH ORDERING (PR 8 satellite): ``pump()``
    #: pops the bucket holding the tightest queued deadline first
    #: instead of FIFO over bucket creation order — through PR 7
    #: classes shaped deadlines but not which bucket dispatched first,
    #: so a tight-deadline batch could sit behind a deadline-less one
    #: for a whole dispatch wall.  Deterministic (ties break on bucket
    #: creation order), so virtual-clock replays stay digest-stable.
    #: ``False`` is the A/B leg (loadbench.slo_ab ``ordering_ab``).
    class_ordering: bool = True
    #: per-class WEIGHTED FAIR QUEUING between SLO classes (ROADMAP
    #: PR-7 follow-on; PR 9 satellite): ``{class: weight}`` — when
    #: set, ``pump()`` orders buckets by normalized service deficit
    #: (lanes already dispatched for the bucket's dominant class,
    #: divided by that class's weight; least-served-per-weight first)
    #: instead of tightest-deadline-first, so a heavy class gets a
    #: proportionally larger share of dispatch slots under sustained
    #: mixed load while light classes can never be starved outright.
    #: Classes absent from the mapping inherit their ``ClassPolicy``
    #: weight.  Deterministic (ties break on bucket creation order) —
    #: virtual-clock replays stay digest-stable.  None (default):
    #: tightest-deadline-first ordering, the PR 8 behavior and the
    #: A/B leg (loadbench ``slo_ab["wfq"]``).
    weights: Optional[Mapping[str, float]] = None
    #: the dispatch-wall estimate is multiplied by this before being
    #: compared against the deadline margin — headroom for the
    #: estimate being an EWMA of a noisy wall
    safety_factor: float = 1.5
    margin_s: float = 0.0
    #: pin the wall estimate for seed-replayable runs (measured EWMAs
    #: differ run to run; a pinned estimate makes every early-flush
    #: decision a pure function of the arrival schedule)
    assumed_dispatch_wall_s: Optional[float] = None
    #: EWMA smoothing for the per-bucket measured wall
    wall_ewma_alpha: float = 0.3

    def __post_init__(self):
        if not self.classes:
            raise ValueError("SLOPolicy needs at least one class")
        if self.default_class not in self.classes:
            raise ValueError(
                f"default_class {self.default_class!r} is not one of "
                f"{sorted(self.classes)}")
        if self.safety_factor < 0.0 or self.margin_s < 0.0:
            raise ValueError("safety_factor and margin_s must be >= 0")
        if not 0.0 < self.wall_ewma_alpha <= 1.0:
            raise ValueError(f"wall_ewma_alpha must be in (0, 1], got "
                             f"{self.wall_ewma_alpha}")
        if self.weights is not None:
            unknown = set(self.weights) - set(self.classes)
            if unknown:
                raise ValueError(
                    f"weights name unknown classes {sorted(unknown)}; "
                    f"expected a subset of {sorted(self.classes)}")
            if any(w <= 0 for w in self.weights.values()):
                raise ValueError("WFQ weights must be > 0 (a zero "
                                 "weight would starve the class "
                                 "outright; leave it out instead)")

    def resolve(self, priority: Optional[str]) -> str:
        """Validate (or default) a submitted priority name."""
        if priority is None:
            return self.default_class
        if priority not in self.classes:
            raise ValueError(f"unknown priority class {priority!r}; "
                             f"expected one of {sorted(self.classes)}")
        return priority

    def deadline_for(self, priority: str) -> Optional[float]:
        return self.classes[priority].deadline_s

    def class_mix(self) -> dict:
        """``{name: weight}`` for the traffic generator."""
        return {name: c.weight for name, c in self.classes.items()}

    def with_early_flush(self, enabled: bool) -> "SLOPolicy":
        return replace(self, early_flush=enabled)

    def with_weights(self, weights: Optional[Mapping[str, float]]
                     ) -> "SLOPolicy":
        return replace(self, weights=weights)

    def weight_of(self, priority: str) -> float:
        """Effective WFQ weight of a class: the ``weights`` entry when
        present, else its ClassPolicy weight (floored at a small
        positive value so an unlisted zero-weight class is still
        schedulable)."""
        if self.weights is not None and priority in self.weights:
            return float(self.weights[priority])
        return max(float(self.classes[priority].weight), 1e-6) \
            if priority in self.classes else 1.0


def default_slo(scale: float = 1.0, early_flush: bool = True,
                assumed_dispatch_wall_s: Optional[float] = None
                ) -> SLOPolicy:
    """The three-class policy the load bench and smoke runs use:
    latency-sensitive ``interactive``, the bulk ``standard`` tier, and
    deadline-less ``batch``.  ``scale`` multiplies the deadlines (CPU
    dispatch walls are seconds; a TPU deployment would scale down)."""
    return SLOPolicy(
        classes={
            "interactive": ClassPolicy(deadline_s=3.0 * scale,
                                       weight=0.35),
            "standard": ClassPolicy(deadline_s=10.0 * scale, weight=0.5),
            "batch": ClassPolicy(deadline_s=None, weight=0.15),
        },
        default_class="standard", early_flush=early_flush,
        assumed_dispatch_wall_s=assumed_dispatch_wall_s)
