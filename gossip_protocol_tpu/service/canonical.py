"""Bucket canonicalization: bound the compile surface of a mixed mix.

PR 15's scenario grammar (25 families x 8 worlds, jittered per
request) drives the exact bucket key (service/bucket.py) toward
every-request-its-own-bucket — each paying a fresh XLA build, the
failure mode continuous-batching servers solve with shape bucketing
(Orca, OSDI'22).  This module collapses near-identical dense trace
requests into CANONICAL equivalence classes along three layers:

* **n pad-ladder** — a dense config's peer count is padded up to the
  next power-of-two rung with INERT filler peers
  (state.pad_schedule_host: never introduced, never known, state rows
  identically zero) and results are sliced back to the real ``n``
  host-side, so ``fleet_shape_key``'s ``n`` component quantizes to
  ladder rungs.
* **plan-signature equivalence classes** — phase windows quantize to
  the ``CHECKPOINT_GRID_TICKS`` grid
  (models/segments.quantized_plan_signature); the exact windows ride
  as Schedule data (``drop_open``/``drop_close`` scalars, the
  injection arrays).
* **runtime world operands** — world parameters the compiled tick
  never bakes (drop probability, byz_boost, wave radius/rate, flap
  knobs, the partition/flap windows, link matrices —
  worlds.OPERAND_WORLD_FIELDS) are dropped from the key entirely;
  only the active-plane SET stays static
  (worlds.canonical_world_key), matching exactly the booleans
  ``core/tick.make_tick`` branches on.  The drop-draw window is the
  ONE window that stays (quantized) key material: it rebuilds the
  class-shared ``drop_active`` cond plane.

Honesty gates: a canonical run must be BIT-IDENTICAL to its exact
solo run (tests/test_canonical.py pins this per tick), and the shared
quantized drop window must keep the draw cond a real cond under vmap
(cond-stays-cond, analysis/jaxpr_audit.py "fleet-dense-canonical").

Scope: canonicalization serves MONOLITHIC dense trace dispatches.
Overlay configs compile ~the whole config statically (their
fleet_shape_key is the config), dense bench mode bakes the
active-corner width, and checkpoint legs validate resume cuts against
the exact plan — all three keep the exact bucket key, and
:func:`canonical_bucket_key` falls back to it.
"""

from __future__ import annotations

from ..config import SimConfig
from ..models.segments import CHECKPOINT_GRID_TICKS, quantize_tick, \
    quantized_plan_signature
from .types import MODES

#: smallest pad-ladder rung: below this every n shares one program
#: anyway and padding overhead is noise
LADDER_MIN = 4


class CanonicalLegUnsupported(NotImplementedError, ValueError):
    """Canonical buckets cannot serve checkpoint legs.

    Legs validate resume cuts against the EXACT segment plan
    (models/segments.py), which is precisely what canonical buckets
    quantize away — a canonical leg would accept cuts its members'
    exact plans reject.  Raised TYPED and EARLY: at ``FleetService``
    construction when ``canonicalize=True`` meets
    ``checkpoint_every``/``checkpoint_every_s``, and at the canonical
    engine's own leg entrypoints (core/fleet.py
    ``CanonicalFleetSimulation.run_leg``/``launch_leg``) for direct
    engine users — never deep inside leg resolve.  Serve legged work
    from exact buckets (``canonicalize=False``); docs/SERVING.md
    'Bucket canonicalization' documents the trade.

    Subclasses both ``NotImplementedError`` (the engine's historical
    spelling for unserved canonical modes) and ``ValueError`` (the
    service's constructor-gate spelling), so both matchers keep
    working."""


def ladder_rung(n: int, multiple: int = 1) -> int:
    """Next power-of-two rung >= max(n, LADDER_MIN) that ``multiple``
    divides.  ``multiple`` must itself be a power of two (the ladder
    doubles, so any other multiple could never be reached): the mesh
    serving path passes its peer-shard count, snapping every rung to
    peer-shard-divisible widths so filler peer rows can never change
    the peer-axis decomposition."""
    m = int(multiple)
    if m < 1 or m & (m - 1):
        raise ValueError(
            f"ladder_rung multiple must be a power of two (the pad "
            f"ladder doubles), got {multiple}")
    r = LADDER_MIN
    while r < n or r % m:
        r *= 2
    return r


def canonical_supported(cfg: SimConfig, mode: str) -> bool:
    """May this request be served from a canonical bucket?  Dense
    trace only (see module docstring for why overlay and bench keep
    exact keys)."""
    return cfg.model != "overlay" and mode == "trace"


def canonical_fleet_shape_key(cfg: SimConfig, peers: int = 1) -> tuple:
    """The pad-ladder twin of ``core/fleet.fleet_shape_key`` for dense
    configs: ``n`` quantizes to its ladder rung, and the worlds tail
    reduces to the static plane booleans the tick actually bakes.

    ``stream_n`` pins the REAL peer count for drop/asym configs: the
    Bernoulli drop lattice is drawn at the real width and embedded
    into the rung (make_tick ``n_active``), so lanes of different real
    n cannot share a drop-on program without changing each other's
    draw stream — no cross-n collapse there, by bit-identity.  Drop-off
    configs never take the draw branch, so their rung programs are
    width-only and collapse across n freely.

    ``peers`` (a power of two; the mesh serving path's FULL-STRENGTH
    peer-shard count) snaps the rung to peer-shard-divisible widths —
    the key carries the snapped rung, not ``peers`` itself, so peer
    counts that land on the same rung still share a class.
    """
    rung = ladder_rung(cfg.n, multiple=peers)
    stream_n = cfg.n if (cfg.drop_msg or cfg.asym_drop) else None
    return ("canon_full_view", rung, stream_n, cfg.t_remove,
            cfg.total_ticks,
            # exactly the static branch booleans of make_tick
            cfg.rejoin_after is not None or cfg.flap_rate > 0,  # churn
            cfg.partition_groups >= 2,                          # partition
            cfg.asym_drop,                                      # asym
            cfg.zombie,                                         # zombie
            cfg.byz_rate > 0,                                   # byz
            cfg.link_latency > 0)                               # latency


def canonical_bucket_key(cfg: SimConfig, mode: str,
                         peers: int = 1) -> tuple:
    """Equivalence-class key: requests with equal keys ride ONE
    compiled canonical program.  Falls back to the exact
    ``bucket_key`` when canonicalization does not apply — the caller
    can always tell which it got (canonical keys lead with
    ``"canon"``).  ``peers`` snaps the pad ladder to peer-shard-
    divisible rungs (see :func:`canonical_fleet_shape_key`); the
    service pins its full-strength peer count here so elastic
    peer-shard shrink never moves a request's bucket key."""
    if mode not in MODES:
        raise ValueError(f"unknown mode {mode!r}; expected one of {MODES}")
    if not canonical_supported(cfg, mode):
        from .bucket import bucket_key
        return bucket_key(cfg, mode)
    return ("canon", mode, canonical_fleet_shape_key(cfg, peers=peers),
            quantized_plan_signature(cfg))


def canonical_drop_window(cfg: SimConfig,
                          grid: int = CHECKPOINT_GRID_TICKS):
    """The bucket-shared quantized drop window ``(open, close)`` —
    a SUPERSET of every member's exact window (lo rounds down, hi
    rounds up), pure function of key material so all lanes of a class
    agree on it by construction.  None when the drop plane is off."""
    if not cfg.drop_msg:
        return None
    return (quantize_tick(cfg.drop_open_tick, grid),
            quantize_tick(cfg.drop_close_tick, grid, up=True))


def canonical_drop_active(cfg: SimConfig,
                          grid: int = CHECKPOINT_GRID_TICKS):
    """bool[T] shared drop plane of a canonical bucket: the quantized
    superset window.  Ticks inside the superset but outside a lane's
    exact window DO take the draw branch — and the draw depends only
    on (rng, tick, stream width), so masking its output with the exact
    per-lane window (make_tick ``lane_drop_window``) reproduces the
    solo run's masks bit-for-bit."""
    import numpy as np
    t = np.arange(cfg.total_ticks, dtype=np.int32)
    win = canonical_drop_window(cfg, grid)
    if win is None:
        return np.zeros(cfg.total_ticks, bool)
    lo, hi = win
    return (t > lo) & (t <= hi)
