"""Seeded open-loop traffic generation for the serving layer.

Every serving number through PR 6 came from a CLOSED-loop replay:
submit all 204 requests, then flush.  Closed loops systematically
understate latency under real arrival processes (Schroeder et al.,
"Open Versus Closed", NSDI'06): a closed loop's next request waits for
the previous one, so the system is never asked to absorb a burst it
didn't just finish serving.  This module is the open-loop side — a
request stream that arrives on ITS schedule, not the service's — with
the same discipline as the PR-5 chaos plane: **every arrival is a pure
function of ``(seed, index)``** on a virtual clock, so a load run
replays digest-for-digest.

Arrival processes (:data:`ARRIVAL_KINDS`):

========  ============================================================
kind      arrival-time law (gaps are rate-modulated exponentials)
========  ============================================================
poisson   homogeneous Poisson at ``rate_rps``
burst     on/off modulation: ``burst_factor`` x the base rate for
          ``duty_cycle`` of each ``period_s``, proportionally quieter
          off-phase (the mean offered load stays ``rate_rps``)
diurnal   one sinusoidal "day" per ``diurnal_period_s`` (required —
          deriving it from the schedule length would make arrival
          times depend on ``n_requests``, breaking the prefix
          invariant below): rate swings ``1 +/- amplitude`` x the
          base, starting at the trough — the ramp-up / peak /
          ramp-down a real service sees
closed    every arrival at t=0 — the degenerate schedule that IS the
          closed-loop replay (service/replay.py), so the old harness
          is a special case of this plane, not a separate code path
========  ============================================================

Each arrival additionally draws — from the same per-index rng — its
scenario template (uniform over the catalog), its lane seed, its
priority class (weighted by the SLO policy's class mix), and its
tenant.  The draw for arrival *i* comes from a fresh
``numpy.random.default_rng((seed, i))``, never mutable RNG state, so
the i-th arrival is identical whatever was asked before it (the same
construction service/faults.py uses for fault schedules); arrival
TIMES are the prefix sums of those per-index gaps, so a schedule's
first k arrivals equal any longer schedule's first k.

Driving a service (:func:`run_schedule`):

* ``pace="wall"`` — the load-bench mode: arrivals are released when
  the real clock passes their scheduled time (never waiting for
  completions — open loop), with cooperative ``pump()`` polling
  between arrivals so time-based and deadline-aware flushes fire.
  Latency numbers are real; under saturation the single-threaded
  service submits late (dispatches block the loop) and the lag is
  reported, not hidden.
* ``pace="virtual"`` — the deterministic mode: the service runs on a
  :class:`VirtualClock` that the driver advances to each arrival's
  scheduled time.  Every scheduling decision (max-wait flushes,
  deadline expiry, SLO early flushes with a pinned wall estimate,
  fault draws) is then a pure function of the schedule, so two runs of
  one seed produce identical outcome digests — the replay gate for
  load runs, chaos included.  The service must be built with
  ``pump_harvest=False`` (or an active injector, which disables the
  harvest anyway): the idle in-flight harvest polls real device
  readiness, which would resolve batches — and stamp their virtual
  completion times — at wall-dependent points.
"""

from __future__ import annotations

import hashlib
import math
import time
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from .replay import Template, build_trace
from .resilience import ShedRejection

#: the arrival-process kinds, in a stable order
ARRIVAL_KINDS = ("poisson", "burst", "diurnal", "closed")


@dataclass(frozen=True)
class TrafficPattern:
    """One arrival process configuration (see the module table)."""

    kind: str = "poisson"
    rate_rps: float = 8.0
    # burst (on/off) modulation
    burst_factor: float = 3.0
    duty_cycle: float = 0.25
    period_s: float = 8.0
    # diurnal sinusoid
    diurnal_amplitude: float = 0.75
    diurnal_period_s: Optional[float] = None

    def __post_init__(self):
        if self.kind not in ARRIVAL_KINDS:
            raise ValueError(f"unknown arrival kind {self.kind!r}; "
                             f"expected one of {ARRIVAL_KINDS}")
        if self.rate_rps <= 0.0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if not 0.0 < self.duty_cycle < 1.0:
            raise ValueError(f"duty_cycle must be in (0, 1), got "
                             f"{self.duty_cycle}")
        if self.kind == "burst" \
                and not 1.0 <= self.burst_factor < 1.0 / self.duty_cycle:
            # off-phase rate = rate * (1 - duty*factor) / (1 - duty)
            # must stay STRICTLY positive (at factor == 1/duty it is
            # exactly 0 and the gap draw divides by it) for the mean
            # to remain rate_rps.  Only checked for burst patterns:
            # the coupled constraint is meaningless for kinds that
            # never read these fields
            raise ValueError(
                f"burst_factor must be in [1, 1/duty_cycle="
                f"{1.0 / self.duty_cycle:.3g}), got {self.burst_factor}")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError(f"diurnal_amplitude must be in [0, 1), got "
                             f"{self.diurnal_amplitude}")
        if self.period_s <= 0.0 or (self.diurnal_period_s is not None
                                    and self.diurnal_period_s <= 0.0):
            raise ValueError("period_s / diurnal_period_s must be > 0")
        if self.kind == "diurnal" and self.diurnal_period_s is None:
            # a default derived from the schedule length (span =
            # n/rate) would make arrival i's gap depend on how many
            # arrivals were ASKED for — breaking the pure-function-of-
            # (seed, index) prefix invariant every other kind keeps
            raise ValueError(
                "diurnal patterns need an explicit diurnal_period_s; "
                "a length-derived default would break the (seed, "
                "index) prefix invariant")

    def local_rate(self, t: float) -> float:
        """Instantaneous offered rate at virtual time ``t``."""
        if self.kind in ("poisson", "closed"):
            return self.rate_rps
        if self.kind == "burst":
            phase = (t % self.period_s) / self.period_s
            if phase < self.duty_cycle:
                return self.rate_rps * self.burst_factor
            return self.rate_rps * (1.0 - self.duty_cycle
                                    * self.burst_factor) \
                / (1.0 - self.duty_cycle)
        # start at the trough (-cos), peak mid-period: the day ramp
        return self.rate_rps * (1.0 - self.diurnal_amplitude
                                * math.cos(2.0 * math.pi * t
                                           / self.diurnal_period_s))


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: everything ``submit()`` needs, stamped
    with its virtual arrival time."""

    idx: int              # 1-based arrival index (the rng index)
    t_s: float            # virtual arrival time
    template: Template
    lane_seed: int
    priority: str
    tenant: str


@dataclass
class TrafficSchedule:
    """A fully-materialized arrival schedule (pure function of its
    seed + pattern + catalog; :meth:`digest` proves it)."""

    arrivals: list
    pattern: TrafficPattern
    seed: int

    def __len__(self) -> int:
        return len(self.arrivals)

    @property
    def span_s(self) -> float:
        return self.arrivals[-1].t_s if self.arrivals else 0.0

    @property
    def offered_rps(self) -> float:
        """Realized offered load (arrivals over the realized span)."""
        return len(self.arrivals) / self.span_s if self.span_s > 0 \
            else float("inf")

    def digest(self) -> str:
        """Stable short hash of the whole arrival schedule — equal
        across two runs iff the same requests arrive at the same
        virtual times with the same template/seed/class/tenant."""
        items = [(a.idx, round(a.t_s, 9), a.template.name,
                  a.template.mode, a.lane_seed, a.priority, a.tenant)
                 for a in self.arrivals]
        return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def make_schedule(templates: Sequence[Template], n_requests: int,
                  pattern: TrafficPattern = TrafficPattern(),
                  seed: int = 0, class_mix: Optional[dict] = None,
                  tenants: Sequence[str] = ("acme", "globex",
                                            "initech", "umbrella")
                  ) -> TrafficSchedule:
    """Generate ``n_requests`` seeded arrivals over the catalog.

    All of arrival *i*'s draws (inter-arrival gap, template, priority
    class, tenant, lane seed) come from one fresh
    ``default_rng((seed, i))``; its arrival time is the prefix sum of
    the gaps.  ``class_mix`` is ``{class_name: weight}`` (e.g.
    ``SLOPolicy.class_mix()``); None means a single ``"standard"``
    class.
    """
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if not templates:
        raise ValueError("make_schedule needs a non-empty catalog")
    mix = class_mix if class_mix else {"standard": 1.0}
    names = tuple(mix)
    weights = np.asarray([mix[k] for k in names], dtype=np.float64)
    if weights.sum() <= 0.0:
        raise ValueError(f"class_mix weights must sum > 0, got {mix}")
    cum = np.cumsum(weights / weights.sum())
    t = 0.0
    arrivals = []
    for i in range(1, n_requests + 1):
        rng = np.random.default_rng((seed, i))
        u_gap, u_tpl, u_cls, u_ten = rng.random(4)
        if pattern.kind != "closed":
            t += -math.log1p(-u_gap) / pattern.local_rate(t)
        tpl = templates[min(int(u_tpl * len(templates)),
                            len(templates) - 1)]
        cls = names[min(int(np.searchsorted(cum, u_cls, side="right")),
                        len(names) - 1)]
        tenant = tenants[min(int(u_ten * len(tenants)),
                             len(tenants) - 1)]
        lane_seed = int(rng.integers(1, 1 << 31))
        arrivals.append(Arrival(idx=i, t_s=t if pattern.kind != "closed"
                                else 0.0, template=tpl,
                                lane_seed=lane_seed, priority=cls,
                                tenant=tenant))
    return TrafficSchedule(arrivals=arrivals, pattern=pattern, seed=seed)


def closed_schedule(templates: Sequence[Template],
                    seeds_per_template: int,
                    priority: str = "standard",
                    tenant: str = "replay") -> TrafficSchedule:
    """The closed-loop replay as a degenerate arrival schedule: the
    EXACT seed-major interleaving ``service.replay.build_trace``
    produces, every arrival at t=0 — so ``run_schedule`` over it is
    the PR-3 replay's serving leg expressed in the traffic plane."""
    arrivals = [Arrival(idx=i + 1, t_s=0.0, template=tpl,
                        lane_seed=s, priority=priority, tenant=tenant)
                for i, (tpl, s) in enumerate(
                    build_trace(templates, seeds_per_template))]
    return TrafficSchedule(
        arrivals=arrivals,
        pattern=TrafficPattern(kind="closed",
                               rate_rps=max(1.0, float(len(arrivals)))),
        seed=-1)


class VirtualClock:
    """A hand-advanced service clock for deterministic traffic runs.

    Pass it as ``FleetService(clock=vc, sleep=vc.sleep)``: every
    deadline, max-wait, and backoff decision then reads schedule time
    instead of wall time.  ``advance_to`` is monotone (a schedule's
    arrival times never rewind the clock)."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance_to(self, t: float) -> None:
        self.t = max(self.t, float(t))

    def sleep(self, dt: float) -> None:
        self.t += max(0.0, float(dt))


def run_schedule(svc, schedule: TrafficSchedule, pace: str = "wall",
                 clock: Optional[VirtualClock] = None,
                 sleep=time.sleep, poll_s: float = 0.002,
                 now=time.perf_counter):
    """Drive one open-loop schedule through a FleetService.

    Returns ``(handles, record)``: ``handles[i]`` is arrival *i*'s
    :class:`~.types.RequestHandle`, or None when admission shed it
    (global depth or tenant quota — recorded in
    ``record["sheds"]``).  The stream is OPEN loop: an arrival is
    released at its scheduled time whether or not earlier requests
    finished; the service is drained at the end, so every returned
    handle is terminal.

    ``record`` carries ``wall_s`` (schedule start to drain end, real
    time under ``"wall"`` pacing / the schedule span under
    ``"virtual"``), ``sheds`` (``(idx, error_type, priority,
    tenant)``), and ``max_lag_s`` — how far submissions fell behind
    schedule (wall pacing only; the cooperative single-threaded
    service submits late when dispatch walls exceed arrival gaps,
    which is exactly what saturation looks like here).
    """
    if pace not in ("wall", "virtual"):
        raise ValueError(f"unknown pace {pace!r}; expected 'wall' or "
                         "'virtual'")
    handles, sheds = [], []

    def _submit(a: Arrival):
        try:
            h = svc.submit(a.template.cfg, seed=a.lane_seed,
                           mode=a.template.mode, priority=a.priority,
                           tenant=a.tenant)
        except ShedRejection as e:
            sheds.append((a.idx, type(e).__name__, a.priority, a.tenant))
            return None
        return h

    if pace == "virtual":
        vclock = clock if clock is not None else svc.clock
        if not isinstance(vclock, VirtualClock) or svc.clock is not vclock:
            raise ValueError(
                "virtual pacing requires the service to run on the "
                "driver's VirtualClock (FleetService(clock=vc, "
                "sleep=vc.sleep))")
        if svc._harvest_enabled():
            raise ValueError(
                "virtual pacing requires pump_harvest=False (or an "
                "active injector): the idle in-flight harvest polls "
                "real device readiness, which would stamp virtual "
                "completion times at wall-dependent points")
        if svc.slo is not None and svc.slo.early_flush \
                and svc.slo.assumed_dispatch_wall_s is None:
            raise ValueError(
                "virtual pacing with deadline-aware early flush "
                "requires SLOPolicy(assumed_dispatch_wall_s=...): the "
                "measured per-bucket wall EWMA differs run to run, so "
                "an unpinned estimate would early-flush at "
                "wall-dependent points and break digest replayability")
        for a in schedule.arrivals:
            vclock.advance_to(a.t_s)
            handles.append(_submit(a))
        svc.drain()
        record = {"pace": pace, "wall_s": schedule.span_s,
                  "sheds": sheds, "max_lag_s": 0.0}
        return handles, record

    t0 = now()
    max_lag = 0.0
    for a in schedule.arrivals:
        while True:
            dt = a.t_s - (now() - t0)
            if dt <= 0.0:
                break
            svc.pump()          # time-based / SLO flushes + harvest
            dt = a.t_s - (now() - t0)
            if dt > 0.0:
                sleep(min(poll_s, dt))
        max_lag = max(max_lag, (now() - t0) - a.t_s)
        handles.append(_submit(a))
    svc.drain()
    record = {"pace": pace, "wall_s": now() - t0, "sheds": sheds,
              "max_lag_s": max_lag}
    return handles, record


def outcome_digest(schedule: TrafficSchedule, handles: list,
                   sheds: list) -> str:
    """Stable short hash of every arrival's terminal outcome —
    status (typed error name for failures), class, tenant, and the
    deadline-missed flag — the load plane's counterpart of the chaos
    plane's ``outcome_digest``.  Every handle must be terminal (run
    after the driver's drain)."""
    shed_idx = {s[0]: s for s in sheds}
    items = []
    for a, h in zip(schedule.arrivals, handles):
        if h is None:
            items.append((a.idx, "shed:"
                          + shed_idx.get(a.idx, (0, "?"))[1],
                          a.priority, a.tenant, None))
            continue
        if not h.done:
            raise RuntimeError(
                f"outcome_digest on a non-terminal handle (rid "
                f"{h.request.rid}, status {h.status}); drain first")
        if h.failed:
            items.append((a.idx, "failed:" + type(h.exception()).__name__,
                          a.priority, a.tenant, None))
        else:
            items.append((a.idx, h.status, a.priority, a.tenant,
                          bool(h.metrics.deadline_missed)))
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]
