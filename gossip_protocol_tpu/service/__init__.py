"""Fleet service: continuous-batching simulation serving.

The layer above the batched engine (core/fleet.py): admit a stream of
heterogeneous ``(config, seed, mode)`` simulation requests, bucket
them by compiled-shape compatibility (shape key + segment-plan
signature), pad partial batches with inert filler lanes, and serve
each bucket through one cached compiled fleet program — per-request
results bit-identical to solo runs, with per-request latency and
per-dispatch occupancy metrics.  With ``mesh=`` (a lane mesh,
parallel/fleet_mesh.py) every dispatch is served from the whole
mesh: capacity ``max_batch x n_devices``, shard-divisible padding,
mesh-keyed program caches.  See docs/SERVING.md.
"""

from .bucket import bucket_key, pad_configs
from .cache import ProgramCache
from .faults import (FAULT_KINDS, FaultInjector, InjectedCompileFailure,
                     InjectedDeviceLoss, InjectedDispatchFailure,
                     InjectedFault)
from .replay import (Template, build_trace, chaos_replay,
                     grader_templates, overlay_templates, replay)
from .resilience import (BreakerPolicy, BucketQuarantined, CircuitBreaker,
                         DeadlineExceeded, DispatchFailed,
                         PoisonedLaneError, RetryPolicy, ServiceError,
                         ShedRejection, solo_execute, solo_run,
                         validate_lane)
from .scheduler import PAD_POLICIES, FleetService
from .types import MODES, RequestHandle, RequestMetrics, SimRequest

__all__ = [
    "FleetService", "ProgramCache", "RequestHandle", "RequestMetrics",
    "SimRequest", "Template", "bucket_key", "build_trace",
    "grader_templates", "overlay_templates", "pad_configs", "replay",
    "chaos_replay", "MODES", "PAD_POLICIES",
    # the failure model (PR 5): the fault plane + resilience machinery
    "FAULT_KINDS", "FaultInjector", "InjectedFault",
    "InjectedCompileFailure", "InjectedDispatchFailure",
    "InjectedDeviceLoss", "RetryPolicy", "BreakerPolicy",
    "CircuitBreaker", "ServiceError", "ShedRejection",
    "DeadlineExceeded", "DispatchFailed", "PoisonedLaneError",
    "BucketQuarantined", "solo_execute", "solo_run", "validate_lane",
]
