"""Fleet service: continuous-batching simulation serving.

The layer above the batched engine (core/fleet.py): admit a stream of
heterogeneous ``(config, seed, mode)`` simulation requests, bucket
them by compiled-shape compatibility (shape key + segment-plan
signature), pad partial batches with inert filler lanes, and serve
each bucket through one cached compiled fleet program — per-request
results bit-identical to solo runs, with per-request latency and
per-dispatch occupancy metrics.  With ``mesh=`` (a lane mesh,
parallel/fleet_mesh.py) every dispatch is served from the whole
mesh: capacity ``max_batch x n_devices``, shard-divisible padding,
mesh-keyed program caches.  The open-loop traffic plane
(service/traffic.py + service/slo.py + service/loadbench.py) drives
the scheduler with seeded arrival processes under SLO-aware
scheduling: priority classes with per-class deadlines, deadline-aware
early flush, per-tenant quotas — every arrival schedule replayable
digest-for-digest.  See docs/SERVING.md.
"""

from .bucket import bucket_key, pad_configs
from .cache import ProgramCache
from .faults import (FAULT_KINDS, FaultInjector, InjectedCompileFailure,
                     InjectedDeviceLoss, InjectedDispatchFailure,
                     InjectedFault)
from .replay import (Template, build_trace, chaos_replay,
                     elastic_replay, grader_templates,
                     overlay_templates, replay, result_digest)
from .resilience import (BreakerPolicy, BucketQuarantined, CircuitBreaker,
                         DeadlineExceeded, DispatchFailed,
                         PoisonedLaneError, RetryPolicy, ServiceError,
                         ShedRejection, TenantQuotaExceeded,
                         solo_execute, solo_resume, solo_run,
                         validate_checkpoint, validate_lane)
from .scheduler import PAD_POLICIES, FleetService
from .slo import ClassPolicy, SLOPolicy, default_slo
from .traffic import (ARRIVAL_KINDS, Arrival, TrafficPattern,
                      TrafficSchedule, VirtualClock, closed_schedule,
                      make_schedule, outcome_digest, run_schedule)
from .types import MODES, RequestHandle, RequestMetrics, SimRequest

__all__ = [
    "FleetService", "ProgramCache", "RequestHandle", "RequestMetrics",
    "SimRequest", "Template", "bucket_key", "build_trace",
    "grader_templates", "overlay_templates", "pad_configs", "replay",
    "chaos_replay", "MODES", "PAD_POLICIES",
    # the failure model (PR 5): the fault plane + resilience machinery
    "FAULT_KINDS", "FaultInjector", "InjectedFault",
    "InjectedCompileFailure", "InjectedDispatchFailure",
    "InjectedDeviceLoss", "RetryPolicy", "BreakerPolicy",
    "CircuitBreaker", "ServiceError", "ShedRejection",
    "DeadlineExceeded", "DispatchFailed", "PoisonedLaneError",
    "BucketQuarantined", "solo_execute", "solo_run", "validate_lane",
    # the open-loop traffic + SLO plane (PR 7): seeded arrival
    # processes, the virtual-clock driver, priority classes, quotas
    "ARRIVAL_KINDS", "Arrival", "TrafficPattern", "TrafficSchedule",
    "VirtualClock", "closed_schedule", "make_schedule",
    "outcome_digest", "run_schedule", "ClassPolicy", "SLOPolicy",
    "default_slo", "TenantQuotaExceeded",
    # the elasticity plane (PR 8): mesh grow + segment-boundary
    # checkpointing + in-flight lane migration
    "elastic_replay", "solo_resume", "validate_checkpoint",
    # the durability plane (PR 12, gossip_protocol_tpu/store/):
    # per-result content digests for the journal + recovery gates
    "result_digest",
]
