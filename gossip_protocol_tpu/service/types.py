"""Request, handle, and metrics types for the fleet service.

A request is one ``(config, seed, mode)`` simulation; the handle is
what ``FleetService.submit`` returns immediately — the serving layer
is continuous-batching, so the work runs later, when the request's
shape bucket flushes (service/scheduler.py).  Everything here is plain
host-side bookkeeping; device work lives entirely in core/fleet.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..config import SimConfig

#: execution modes a request can ask for.  ``trace`` is the full-event
#: path (dense models) / metrics path (overlay) — what the grader
#: consumes; ``bench`` is the counters-only whole-run-on-device path.
MODES = ("trace", "bench")


@dataclass
class SimRequest:
    """One admitted simulation request.

    Immutable once queued, with one scheduler-owned exception: under
    checkpointed serving (PR 8) the scheduler advances ``resume`` (the
    lane's latest snapshot) and ``bucket`` (the resume sub-bucket the
    request re-queues under) as legs complete.
    """

    rid: int              # service-assigned id, submission order
    cfg: SimConfig        # the lane's full config (seed included)
    mode: str             # one of MODES
    bucket: tuple         # compatibility key (service/bucket.py)
    submit_s: float       # service clock at admission
    #: absolute service-clock deadline (None: no deadline).  Queued
    #: requests past it fail fast with DeadlineExceeded; dispatched
    #: requests that complete late are accounted in
    #: ``RequestMetrics.deadline_missed`` — never silently dropped.
    deadline_s: Optional[float] = None
    #: SLO priority class (service/slo.py): validated against the
    #: service's policy when one is set (and supplying the default
    #: deadline above), a free-form label otherwise; always feeds the
    #: per-class stats windows
    priority: str = "default"
    #: tenant attribution for per-tenant admission quotas and shed
    #: accounting (None: untenanted — never quota-limited)
    tenant: Optional[str] = None
    #: the lane's latest segment-boundary checkpoint
    #: (core/fleet.LaneCheckpoint) when the request runs as resumable
    #: legs (PR 8 elastic serving, ``FleetService(checkpoint_every=)``).
    #: Set by the scheduler when a non-final leg resolves; the request
    #: then re-queues under a resume sub-bucket and its next dispatch
    #: re-enters the scan from this snapshot — never from tick 0.
    #: With a spill tier attached (PR 12, ``FleetService(run_dir=)``)
    #: this may be a store.spill.SpilledCheckpoint proxy instead of a
    #: resident LaneCheckpoint — same digest/cfg/tick surface, state
    #: loaded (and validated) from disk only at dispatch.
    #: Cleared at completion.
    resume: Optional[object] = None


@dataclass
class RequestMetrics:
    """Per-request serving metrics, filled at completion.

    ``queue_wait_s + run_wall_s <= latency_s`` (latency also counts
    host-side unstacking).  ``occupancy`` is the real-lane fraction of
    the dispatched program this request rode in; ``cache_hit`` is True
    when the dispatch reused an already-built fleet program (zero new
    whole-run builds, ``core.tick.run_build_count``).
    """

    rid: int
    bucket: tuple
    mode: str
    queue_wait_s: float
    run_wall_s: float
    latency_s: float
    batch: int            # real lanes in the dispatch
    padded_batch: int     # compiled width actually dispatched
    occupancy: float      # batch / padded_batch
    cache_hit: bool
    builds: int           # whole-run builds this dispatch triggered
    #: failed dispatch attempts this request's batch survived before
    #: completing (0 on the clean path)
    retries: int = 0
    #: True when the request was served by the solo-run fallback (the
    #: degradation ladder's bottom rung, service/resilience.py) rather
    #: than a batched fleet program
    degraded: bool = False
    #: True when the request completed AFTER its deadline (the result
    #: is still delivered; expiry BEFORE dispatch fails the handle
    #: with DeadlineExceeded instead)
    deadline_missed: bool = False
    #: the request's SLO class and tenant, copied from the request so
    #: per-class/per-tenant analysis needs only the metrics stream
    priority: str = "default"
    tenant: Optional[str] = None
    #: dispatches this request rode to completion: 1 on the monolithic
    #: path; the number of resumable legs under checkpointed serving
    #: (PR 8) — each leg re-entered the scan from the previous leg's
    #: segment-boundary snapshot
    legs: int = 1


@dataclass
class RequestHandle:
    """Future-like handle for a submitted request.

    ``result()`` returns the lane's :class:`~..core.sim.SimResult`
    (dense) or :class:`~..models.overlay.OverlayResult` (overlay) —
    bit-identical to running the request's config alone
    (tests/test_service.py).  If the request is still queued,
    ``result()`` flushes its bucket first, so it never deadlocks on a
    partial batch that would otherwise wait for ``max_wait``.

    Every handle reaches a TERMINAL state — ``completed``,
    ``degraded`` (served by the solo-run fallback), or ``failed``
    (``result()`` re-raises the typed error: DeadlineExceeded,
    DispatchFailed, ... — service/resilience.py).  The scheduler's
    dispatch path is atomic about this: a request popped for a
    dispatch is never left ``pending`` with no owner, whatever the
    dispatch did (tests/test_resilience.py).
    """

    request: SimRequest
    _service: "FleetService" = field(repr=False)  # noqa: F821
    _result: Optional[object] = field(default=None, repr=False)
    _metrics: Optional[RequestMetrics] = field(default=None, repr=False)
    _error: Optional[BaseException] = field(default=None, repr=False)
    #: set by the pipelined scheduler when the request's batch has been
    #: LAUNCHED on device but not yet resolved (cleared if the batch is
    #: re-queued by an interrupted dispatch)
    _launched: bool = field(default=False, repr=False)

    @property
    def done(self) -> bool:
        """Terminal (completed, degraded, or failed)."""
        return self._metrics is not None or self._error is not None

    @property
    def failed(self) -> bool:
        return self._error is not None

    @property
    def status(self) -> str:
        """``pending`` | ``in_flight`` | ``completed`` | ``degraded``
        | ``failed``.  ``in_flight`` (pipelined scheduling, PR 6)
        means the batch's device program is launched and executing;
        ``result()``/``flush()`` resolves it."""
        if self._error is not None:
            return "failed"
        if self._metrics is not None:
            return "degraded" if self._metrics.degraded else "completed"
        return "in_flight" if self._launched else "pending"

    def exception(self) -> Optional[BaseException]:
        """The terminal error (None unless :attr:`failed`)."""
        return self._error

    def result(self):
        # under checkpointed serving (PR 8) a flush of the request's
        # bucket may CHECKPOINT its batch and re-queue it under the
        # next leg's resume sub-bucket (request.bucket is updated in
        # place) — keep flushing the request's CURRENT bucket until it
        # is terminal; each flush advances the run by at least one
        # leg, so zero dispatches without a terminal state means the
        # flush was interrupted
        while not self.done:
            bucket = self.request.bucket
            n = self._service.flush(bucket)
            if self.done:
                break
            if n == 0 and self.request.bucket == bucket:
                # a flush can legitimately dispatch NOTHING yet still
                # advance this request: resolving an in-flight
                # pipelined leg checkpoints the batch and re-queues it
                # one cut further (request.bucket moves) — only a
                # zero-dispatch flush that left the request in the
                # SAME bucket is stuck.  Unreachable through the
                # scheduler's atomic dispatch path; kept as a guard
                # against interrupted flushes (KeyboardInterrupt
                # re-queues the batch and propagates)
                raise RuntimeError(
                    f"request {self.request.rid} is still pending "
                    "after a flush of its bucket; the flush was "
                    "interrupted — flush again")
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def metrics(self) -> RequestMetrics:
        if not self.done:
            self.result()
        if self._error is not None:
            raise self._error
        return self._metrics

    def _complete(self, result, metrics: RequestMetrics) -> None:
        self._result = result
        self._metrics = metrics

    def _fail(self, error: BaseException) -> None:
        self._error = error
