"""Request, handle, and metrics types for the fleet service.

A request is one ``(config, seed, mode)`` simulation; the handle is
what ``FleetService.submit`` returns immediately — the serving layer
is continuous-batching, so the work runs later, when the request's
shape bucket flushes (service/scheduler.py).  Everything here is plain
host-side bookkeeping; device work lives entirely in core/fleet.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..config import SimConfig

#: execution modes a request can ask for.  ``trace`` is the full-event
#: path (dense models) / metrics path (overlay) — what the grader
#: consumes; ``bench`` is the counters-only whole-run-on-device path.
MODES = ("trace", "bench")


@dataclass
class SimRequest:
    """One admitted simulation request (immutable once queued)."""

    rid: int              # service-assigned id, submission order
    cfg: SimConfig        # the lane's full config (seed included)
    mode: str             # one of MODES
    bucket: tuple         # compatibility key (service/bucket.py)
    submit_s: float       # service clock at admission


@dataclass
class RequestMetrics:
    """Per-request serving metrics, filled at completion.

    ``queue_wait_s + run_wall_s <= latency_s`` (latency also counts
    host-side unstacking).  ``occupancy`` is the real-lane fraction of
    the dispatched program this request rode in; ``cache_hit`` is True
    when the dispatch reused an already-built fleet program (zero new
    whole-run builds, ``core.tick.run_build_count``).
    """

    rid: int
    bucket: tuple
    mode: str
    queue_wait_s: float
    run_wall_s: float
    latency_s: float
    batch: int            # real lanes in the dispatch
    padded_batch: int     # compiled width actually dispatched
    occupancy: float      # batch / padded_batch
    cache_hit: bool
    builds: int           # whole-run builds this dispatch triggered


@dataclass
class RequestHandle:
    """Future-like handle for a submitted request.

    ``result()`` returns the lane's :class:`~..core.sim.SimResult`
    (dense) or :class:`~..models.overlay.OverlayResult` (overlay) —
    bit-identical to running the request's config alone
    (tests/test_service.py).  If the request is still queued,
    ``result()`` flushes its bucket first, so it never deadlocks on a
    partial batch that would otherwise wait for ``max_wait``.
    """

    request: SimRequest
    _service: "FleetService" = field(repr=False)  # noqa: F821
    _result: Optional[object] = field(default=None, repr=False)
    _metrics: Optional[RequestMetrics] = field(default=None, repr=False)

    @property
    def done(self) -> bool:
        return self._metrics is not None

    def result(self):
        if not self.done:
            self._service.flush(self.request.bucket)
        if not self.done:
            # reachable only if a flush dispatched and failed (the
            # scheduler re-queues the batch then re-raises, so the
            # caller normally sees the dispatch error first)
            raise RuntimeError(
                f"request {self.request.rid} is still pending after a "
                "flush of its bucket; a previous dispatch of this "
                "bucket failed — fix the error and flush again")
        return self._result

    @property
    def metrics(self) -> RequestMetrics:
        if not self.done:
            self.result()
        return self._metrics

    def _complete(self, result, metrics: RequestMetrics) -> None:
        self._result = result
        self._metrics = metrics
