"""Shape-bucketing: which requests may ride one compiled program.

A fleet program bakes a config *shape* (core/fleet.fleet_shape_key)
and — on paths that specialize per schedule phase — the segment plan
(models/segments.plan_signature).  The bucket key is exactly that
union, plus the execution mode and, for dense bench requests, the
static active-corner width (a bench fleet compiles ONE width, so
lanes must agree on ``active_bound`` up front rather than fail inside
``FleetSimulation.run_bench``).

Everything NOT in the key flows through the Schedule arrays as data
(seeds, victim draws, drop realizations), which is precisely why
batching within a bucket is exact: per-lane results stay bit-identical
to solo runs.  The key errs conservative — e.g. two dense trace
configs differing only in ``drop_open_tick`` could share today's
compiled program (the window is schedule data there), but they get
separate buckets because the grid-kernel path does bake that boundary
and a serving layer must never depend on which engine path a bucket
lands on.

Partial batches are padded with FILLER lanes: replicas of the
bucket's first-seen config (same shape by construction, seed
irrelevant — filler results are masked out device-side and never
unstacked, core/fleet.py ``n_real``).

This EXACT key is one end of a dial.  Under a jittered mixed stream
(the PR 15 scenario grammar) it degenerates toward one bucket — and
one fresh XLA build — per request; ``FleetService(canonicalize=True)``
buckets by the CANONICAL equivalence-class key instead
(service/canonical.py: pad-ladder rungs over ``n``, quantized phase
windows, world parameters as runtime operands), collapsing that
stream to one program per class while staying bit-identical per lane.
The exact key remains the fallback for everything canonicalization
does not serve (overlay, bench, checkpoint legs) and the MEMBER
identity recorded per class (ProgramCache.stats()["classes"]).
"""

from __future__ import annotations

from ..config import SimConfig
from ..core.fleet import fleet_shape_key
from ..models.segments import plan_signature
from .types import MODES


def bucket_key(cfg: SimConfig, mode: str) -> tuple:
    """Compatibility key: requests with equal keys batch together."""
    if mode not in MODES:
        raise ValueError(f"unknown mode {mode!r}; expected one of {MODES}")
    key = (mode, fleet_shape_key(cfg), plan_signature(cfg))
    if cfg.model != "overlay":
        # the plan signature pins the drop WINDOW but not the
        # probability; one bucket must share the whole drop plan so
        # the fleet can keep it unbatched (core/fleet.py
        # SCHED_AXES_SHARED_DROP) — a mixed-prob bucket would silently
        # degrade to the batched-drop program and compile twice
        key += (cfg.msg_drop_prob if cfg.drop_msg else None,)
    if mode == "bench" and cfg.model != "overlay":
        from ..core.dense_corner import active_bound
        key += (active_bound(cfg),)
    return key


def pad_configs(cfgs: list, width: int, filler: SimConfig) -> list:
    """Pad a partial batch to ``width`` lanes with inert filler."""
    if len(cfgs) > width:
        raise ValueError(f"batch of {len(cfgs)} exceeds width {width}")
    return list(cfgs) + [filler] * (width - len(cfgs))
