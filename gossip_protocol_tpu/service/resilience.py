"""Resilience machinery for the fleet service: the policies and typed
errors that let the scheduler survive the fault plane (service/
faults.py) — and real failures — without ever stranding a request.

The contract this module exists to enforce (the PR-5 tentpole): every
request popped for a dispatch reaches a TERMINAL state before the
dispatch returns — completed, completed-degraded (served by the
solo-run fallback), or failed with a typed error on its handle.  The
pre-PR-5 scheduler re-queued a failed batch and re-raised out of the
caller's flush, which left handles pending with no owner; the new
``FleetService._serve_batch`` drives this module's pieces instead:

* :class:`RetryPolicy` — bounded retries with exponential backoff and
  deterministic seeded jitter (replayable chaos runs need replayable
  backoff), capped so a retry storm cannot stall the service;
* deadlines — a request may carry an absolute deadline; expired
  requests fail fast with :class:`DeadlineExceeded` (queue expiry in
  ``pump``/``flush``, in-dispatch expiry between retries) and
  late-but-completed requests are *accounted* (``RequestMetrics.
  deadline_missed``), never silently dropped;
* :class:`CircuitBreaker` — per-bucket consecutive-failure breaker:
  an open bucket is quarantined (its dispatches go straight to the
  solo-run fallback, so one hot broken bucket cannot burn retries
  forever) and half-opens after a cooldown for one probe dispatch;
* admission control — a bounded queue sheds with the typed
  :class:`ShedRejection` at ``submit`` time, never by dropping a
  queued request;
* :func:`validate_lane` — cheap per-lane sanity (tick completeness,
  non-negative counters) that turns a poisoned result into a typed,
  retryable failure instead of a silently wrong answer;
* :func:`solo_run` — the degradation ladder's bottom rung: one
  request, one direct single-simulation run, no fleet program, no
  mesh.  It is the same execution the parity harness uses as its
  reference, so a degraded request is still served a correct result.

The degradation ladder, top to bottom: full mesh -> shrunken mesh
(``parallel.fleet_mesh.shrink_mesh``, driven by the scheduler on
device loss) -> single device -> solo run.  Each rung preserves
correctness and sheds only throughput.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


# ---- typed errors ----------------------------------------------------
class ServiceError(RuntimeError):
    """Base of every error the serving layer itself raises."""


class ShedRejection(ServiceError):
    """Admission refused: the service queue is at ``max_queue_depth``.

    Raised from ``submit()`` BEFORE a handle exists — the typed "try
    again later" of load shedding.  Nothing already queued is ever
    dropped to make room."""

    def __init__(self, pending: int, max_queue_depth: int):
        self.pending = pending
        self.max_queue_depth = max_queue_depth
        super().__init__(
            f"request shed: {pending} requests already queued >= "
            f"max_queue_depth={max_queue_depth}; drain or retry later")


class TenantQuotaExceeded(ShedRejection):
    """Admission refused for ONE tenant: it already holds
    ``tenant_quota`` queued requests (the per-tenant layer on top of
    ``max_queue_depth`` — one hot tenant cannot starve the queue).
    A ShedRejection, so callers that back off on global shedding
    handle it unchanged; nothing queued is ever dropped."""

    def __init__(self, tenant: str, queued: int, quota: int):
        self.tenant = tenant
        self.queued = queued
        self.quota = quota
        # ShedRejection's fields, for callers that read them generically
        self.pending = queued
        self.max_queue_depth = quota
        ServiceError.__init__(
            self, f"request shed for tenant {tenant!r}: {queued} "
            f"requests already queued >= tenant_quota={quota}; other "
            "tenants are unaffected")


class DeadlineExceeded(ServiceError):
    """The request's deadline passed before it could be served."""

    def __init__(self, rid: int, waited_s: float, budget_s: float):
        self.rid = rid
        self.waited_s = waited_s
        self.budget_s = budget_s
        super().__init__(
            f"request {rid} exceeded its deadline: waited "
            f"{waited_s:.3f}s of a {budget_s:.3f}s budget")


class PoisonedLaneError(ServiceError):
    """Per-lane validation failed on a dispatched result — the lane is
    corrupt (injected or real) and the dispatch must not complete."""

    def __init__(self, rid: int, why: str):
        self.rid = rid
        super().__init__(f"lane for request {rid} failed validation: "
                         f"{why}")


class BucketQuarantined(ServiceError):
    """The bucket's circuit breaker is open; batched dispatches are
    suspended and its requests ride the solo fallback."""

    def __init__(self, key: tuple):
        self.bucket = key
        super().__init__(
            f"bucket {key!r} is quarantined by its circuit breaker; "
            "requests are degraded to solo runs until the cooldown "
            "probe succeeds")


class DispatchFailed(ServiceError):
    """Terminal request failure: retries exhausted (and the solo
    fallback failed or was disabled).  ``__cause__`` carries the last
    underlying error."""

    def __init__(self, rid: int, attempts: int, last_error):
        self.rid = rid
        self.attempts = attempts
        super().__init__(
            f"request {rid} failed after {attempts} dispatch "
            f"attempt(s): {type(last_error).__name__}: {last_error}")


# ---- retry policy ----------------------------------------------------
@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff and seeded jitter.

    ``backoff_s(attempt)`` for attempt 1, 2, ... is
    ``base * factor**(attempt-1)`` capped at ``max_backoff_s``, times
    a deterministic jitter in ``[1 - jitter_frac, 1 + jitter_frac]``
    drawn from ``(seed, attempt, salt)`` — deterministic so chaos
    replays reproduce their own timing decisions, jittered so real
    deployments don't synchronize retry storms."""

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    max_backoff_s: float = 2.0
    jitter_frac: float = 0.25
    seed: int = 0

    def backoff_s(self, attempt: int, salt: int = 0) -> float:
        base = min(self.max_backoff_s,
                   self.backoff_base_s
                   * self.backoff_factor ** max(0, attempt - 1))
        if self.jitter_frac <= 0.0:
            return base
        rng = np.random.default_rng((self.seed, attempt, salt))
        return base * (1.0 + self.jitter_frac
                       * (2.0 * float(rng.random()) - 1.0))


# ---- circuit breaker -------------------------------------------------
@dataclass(frozen=True)
class BreakerPolicy:
    """Open a bucket after ``failure_threshold`` CONSECUTIVE failed
    dispatch attempts; half-open one probe after ``reset_after_s`` on
    the service clock."""

    failure_threshold: int = 3
    reset_after_s: float = 30.0


class CircuitBreaker:
    """Per-bucket consecutive-failure circuit breaker.

    closed -> (threshold consecutive failures) -> open: ``allow``
    returns False and the scheduler quarantines the bucket (solo
    fallback).  After ``reset_after_s``, ``allow`` grants ONE probe
    dispatch (half-open): success closes the breaker, failure
    re-opens it and restarts the cooldown."""

    def __init__(self, policy: BreakerPolicy):
        self.policy = policy
        self._state: dict = {}   # key -> {"fails": int, "opened_at": t}

    def _s(self, key):
        return self._state.setdefault(key, {"fails": 0, "opened_at": None})

    def allow(self, key, now: float) -> bool:
        s = self._s(key)
        if s["opened_at"] is None:
            return True
        return now - s["opened_at"] >= self.policy.reset_after_s

    def is_open(self, key, now: float) -> bool:
        return not self.allow(key, now)

    def record_failure(self, key, now: float) -> bool:
        """Count one failed attempt; returns True when this transition
        OPENS the breaker (re-arming an already-open breaker after a
        failed probe refreshes the cooldown but returns False)."""
        s = self._s(key)
        s["fails"] += 1
        if s["fails"] >= self.policy.failure_threshold:
            newly = s["opened_at"] is None
            s["opened_at"] = now
            return newly
        return False

    def record_success(self, key) -> None:
        self._state[key] = {"fails": 0, "opened_at": None}

    def open_buckets(self, now: float) -> int:
        return sum(1 for k in self._state if self.is_open(k, now))


# ---- lane validation -------------------------------------------------
def validate_lane(req, lane) -> Optional[str]:
    """Cheap sanity on one dispatched lane; returns the violation (or
    None).  Checks exactly the invariants every correct run satisfies
    — the full tick count executed, message counters non-negative —
    which is what a poisoned lane (service/faults.py) breaks.  Runs
    host-side on already-transferred arrays, so its cost is a scan of
    the per-lane counter stacks, not a device round-trip."""
    exp = req.cfg.total_ticks
    run = getattr(lane, "ticks_run", exp)
    if run != exp:
        return f"ran {run} of {exp} ticks"
    sent = np.asarray(lane.metrics.sent if hasattr(lane, "metrics")
                      else lane.sent)
    if sent.size and int(sent.min()) < 0:
        return "negative message counters"
    return None


def validate_checkpoint(req, ck) -> Optional[str]:
    """Per-lane sanity on a non-final resolved LEG (PR 8 elastic
    serving): the snapshot's clock advanced and the leg's own output
    chunk carries sane counters — which is what a poisoned leg
    (service/faults.py) breaks.  A failing leg is retried from the
    PREVIOUS checkpoint, exactly like any other dispatch failure."""
    if ck.tick <= 0 or ck.tick > req.cfg.total_ticks:
        return f"checkpoint clock {ck.tick} outside (0, " \
               f"{req.cfg.total_ticks}]"
    if not ck.chunks:
        return "checkpoint carries no output chunks"
    chunk = ck.chunks[-1]
    sent = np.asarray(chunk.sent if hasattr(chunk, "sent")
                      else chunk[2])
    if sent.size and int(sent.min()) < 0:
        return "negative message counters in the checkpointed segment"
    return None


# ---- the degradation ladder's bottom rung ----------------------------
def solo_execute(cfg, mode: str):
    """ONE direct single-simulation execution — no fleet program, no
    mesh, no injector.  This single implementation is shared by the
    degradation fallback (:func:`solo_run`) and the replay harness's
    sequential parity leg (service/replay.py ``_solo_run``), which is
    what makes "the solo fallback IS the parity reference" a
    structural fact rather than a convention two copies could drift
    out of."""
    if cfg.model == "overlay":
        from ..models.overlay import OverlaySimulation
        return OverlaySimulation(cfg, use_pallas=False).run()
    from ..core.sim import Simulation
    sim = Simulation(cfg)
    return sim.run_bench() if mode == "bench" else sim.run()


def solo_run(req):
    """Serve one request by :func:`solo_execute` — the degradation
    ladder's bottom rung.  A degraded request still gets a correct
    (reference-grade) result; what it gives up is batched throughput,
    not fidelity.  (One visible difference for overlay requests: a
    solo run computes real ``live_uncovered`` coverage where fleet
    lanes report the kernels' -1 sentinel — which is why the chaos
    gate promises bit-parity for non-degraded requests and
    correctness for degraded ones.)"""
    return solo_execute(req.cfg, req.mode)


def solo_resume(req):
    """The bottom rung for a CHECKPOINTED request (PR 8): resume the
    lane's solo continuation from its latest segment-boundary snapshot
    instead of re-running from tick 0, then stitch the accumulated
    chunks into the full-horizon result through the same assembly the
    fleet path uses (core/fleet.finish_lane) — so even a request that
    falls all the way down the ladder never loses checkpointed work,
    and its result stays bit-identical to an uninterrupted solo run
    (the schedule is closed-form in the carried clock)."""
    import dataclasses as _dc

    import jax

    from ..core.fleet import finish_lane
    ck = req.resume
    if hasattr(ck, "load"):
        # durable serving: req.resume is a lightweight
        # store/spill.SpilledCheckpoint proxy — fetch the real
        # snapshot (RAM hit or validated disk reload)
        ck = ck.load()
    cfg = ck.cfg
    if cfg.model == "overlay":
        from ..models.overlay import (OverlaySimulation,
                                      overlay_state_from_host)
        state = overlay_state_from_host(
            {**ck.state, "tick": np.int32(ck.tick)})
        res = OverlaySimulation(cfg, use_pallas=False).run(
            resume_from=state)
        final = res.final_state
        chunk = jax.tree.map(np.asarray, res.metrics)
    else:
        from ..core.sim import Simulation
        from ..state import state_from_host
        state = state_from_host({**ck.state, "tick": np.int32(ck.tick)})
        res = Simulation(cfg).run(resume_from=state)
        final = res.final_state
        # solo SimResult counters are (N, T_segment); chunks ride (T, N)
        chunk = (res.added, res.removed, res.sent.T, res.recv.T)
    done = _dc.replace(
        ck, tick=cfg.total_ticks,
        state={f.name: np.asarray(getattr(final, f.name))
               for f in _dc.fields(type(final)) if f.name != "tick"},
        chunks=list(ck.chunks) + [chunk],
        wall_seconds=ck.wall_seconds + res.wall_seconds,
        legs=ck.legs + 1, mesh_desc=None)
    return finish_lane(done)
