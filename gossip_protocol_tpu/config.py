"""Configuration system.

TPU-native replacement for the reference's ``Params`` class
(reference: Params.h:21-36, Params.cpp:19-50).  The reference reads a
4-line positional ``.conf`` file (Params.cpp:22-25) and derives everything
else from compile-time constants (Application.h:27 TOTAL_RUNNING_TIME=700,
MP1Node.h:21-22 TREMOVE=20/TFAIL=5, EmulNet.h:10-12 buffer limits,
Params.cpp:29-31 STEP_RATE/MAX_MSG_SIZE/PORTNUM).

Here everything is one frozen dataclass.  The legacy ``.conf`` grammar is
still ingested by :func:`SimConfig.from_conf` so the reference's
``testcases/*.conf`` files work unmodified, and extended knobs (seed,
peer count overrides, topology family, churn) are first-class fields
instead of hardcoded constants.
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass
from typing import Optional

#: Index (0-based) of the introducer/coordinator peer.  The reference
#: hardwires the join address to id=1:port=0 (Application.cpp:209-217,
#: MP1Node.cpp:378-386); ids are assigned sequentially from 1
#: (EmulNet.cpp:72-77), so the introducer is always peer index 0.
INTRODUCER = 0


@dataclass(frozen=True)
class SimConfig:
    """All parameters of one simulation scenario.

    Field names follow the reference's .conf keys where they exist
    (Params.cpp:22-25); the rest mirror the reference's compile-time
    constants with the same defaults.
    """

    # --- legacy .conf fields (Params.cpp:22-25) ---
    max_nnb: int = 10            # MAX_NNB -> number of peers (EN_GPSZ = MAX_NNB, Params.cpp:29)
    single_failure: bool = True  # SINGLE_FAILURE
    drop_msg: bool = False       # DROP_MSG
    msg_drop_prob: float = 0.1   # MSG_DROP_PROB

    # --- reference compile-time constants ---
    total_ticks: int = 700       # TOTAL_RUNNING_TIME (Application.h:27)
    step_rate: float = 0.25      # Params.cpp:30; node i starts at int(step_rate*i)
    t_remove: int = 20           # TREMOVE (MP1Node.h:21)
    t_fail: int = 5              # TFAIL (MP1Node.h:22) — vestigial in the reference too
    portnum: int = 8001          # Params.cpp:12 — note ENinit still assigns port 0
    max_msg_size: int = 4000     # Params.cpp:31
    en_buff_size: int = 30000    # ENBUFFSIZE (EmulNet.h:12)
    fail_tick: int = 100         # failure injection time (Application.cpp:181,188)
    drop_open_tick: int = 50     # drop window opens (Application.cpp:177)
    drop_close_tick: int = 300   # drop window closes (Application.cpp:198)

    # --- new framework knobs (absent in the reference) ---
    #: PRNG seed.  The reference uses ``srand(time(NULL))`` twice
    #: (Application.cpp:50,96) so its runs are irreproducible; we default
    #: to a fixed seed and treat reproducibility as a feature.
    seed: int = 0
    #: Protocol/model family: "full_view" reproduces the reference's
    #: all-pairs full-list heartbeating; "overlay" is the bounded
    #: partial-view family for very large N (BASELINE.json 65k/1M configs).
    model: str = "full_view"
    #: Overlay exchange fanout (only used by model="overlay");
    #: 0 = auto (~log2(N)/2 + 2, see models/overlay.py resolved_dims).
    fanout: int = 0
    #: Overlay view capacity K (slots per node; models/overlay.py).
    #: 0 = auto (~4*log2 N, capped at 64).  Right-sizing matters: too
    #: large a view at small N starves slots of merge candidates.
    overlay_view: int = 0
    #: Overlay payload sample L: view slots carried per message
    #: (rotating window; full view every K/L ticks).  0 = auto (K/2).
    overlay_sample: int = 0
    #: Exchange-graph degree family (overlay only).  "uniform": every
    #: node gossips on all F rounds each tick (Erdős–Rényi-flavored —
    #: the BASELINE 65k shape).  "powerlaw": per-node out-degrees
    #: follow a bounded Pareto tail (P[deg >= k] ~ k^-(alpha-1), the
    #: BASELINE 1M scale-free shape): a few hubs gossip on many rounds,
    #: most nodes on few.  Degrees are a static seeded node property.
    topology: str = "uniform"
    #: Pareto tail exponent for topology="powerlaw".
    powerlaw_alpha: float = 2.5
    #: Churn rate per tick (overlay extension; 0 disables).
    churn_rate: float = 0.0
    #: Churn/rejoin extension (SURVEY.md §5 — the reference never
    #: re-admits a failed node): failed peers are wiped and re-introduced
    #: ``rejoin_after`` ticks after their failure, rejoining through the
    #: normal JOINREQ path.  None disables (reference behavior).
    rejoin_after: Optional[int] = None

    @property
    def n(self) -> int:
        """Number of peers (the reference's EN_GPSZ, Params.cpp:29)."""
        return self.max_nnb

    def start_tick(self, i: int) -> int:
        """Tick at which peer index ``i`` is introduced.

        Reference: nodes start when ``t == (int)(STEP_RATE*i)``
        (Application.cpp:143), i.e. C truncation of 0.25*i.
        """
        return int(self.step_rate * i)

    def replace(self, **kw) -> "SimConfig":
        return dataclasses.replace(self, **kw)

    # --- legacy .conf ingestion -------------------------------------
    @classmethod
    def from_conf(cls, path: str, **overrides) -> "SimConfig":
        """Parse a reference-format .conf file (Params.cpp:22-25).

        The reference reads exactly four ``KEY: value`` lines in fixed
        order with fscanf; we accept them in any order and ignore
        unknown keys, but the three shipped testcases parse bit-identically.
        """
        keys = {}
        with open(path, "r") as f:
            for line in f:
                m = re.match(r"\s*([A-Z_]+)\s*:\s*([0-9.eE+-]+)", line)
                if m:
                    keys[m.group(1)] = m.group(2)
        if "MAX_NNB" not in keys and "max_nnb" not in overrides:
            # A conf that never mentions MAX_NNB is malformed or
            # mis-pathed (the reference's positional fscanf would read
            # garbage, Params.cpp:22-25); refuse to silently simulate
            # the defaults.  native/params.cc applies the same rule.
            raise ValueError(f"no MAX_NNB key in {path}")
        kw = {}
        if "MAX_NNB" in keys:
            kw["max_nnb"] = int(keys["MAX_NNB"])
        if "SINGLE_FAILURE" in keys:
            kw["single_failure"] = bool(int(keys["SINGLE_FAILURE"]))
        if "DROP_MSG" in keys:
            kw["drop_msg"] = bool(int(keys["DROP_MSG"]))
        if "MSG_DROP_PROB" in keys:
            kw["msg_drop_prob"] = float(keys["MSG_DROP_PROB"])
        kw.update(overrides)
        return cls(**kw)


#: The three scenarios shipped with the reference (testcases/*.conf).
SINGLE_FAILURE = SimConfig(max_nnb=10, single_failure=True, drop_msg=False)
MULTI_FAILURE = SimConfig(max_nnb=10, single_failure=False, drop_msg=False)
MSG_DROP_SINGLE_FAILURE = SimConfig(max_nnb=10, single_failure=True, drop_msg=True,
                                    msg_drop_prob=0.1)
