"""Configuration system.

TPU-native replacement for the reference's ``Params`` class
(reference: Params.h:21-36, Params.cpp:19-50).  The reference reads a
4-line positional ``.conf`` file (Params.cpp:22-25) and derives everything
else from compile-time constants (Application.h:27 TOTAL_RUNNING_TIME=700,
MP1Node.h:21-22 TREMOVE=20/TFAIL=5, EmulNet.h:10-12 buffer limits,
Params.cpp:29-31 STEP_RATE/MAX_MSG_SIZE/PORTNUM).

Here everything is one frozen dataclass.  The legacy ``.conf`` grammar is
still ingested by :func:`SimConfig.from_conf` so the reference's
``testcases/*.conf`` files work unmodified, and extended knobs (seed,
peer count overrides, topology family, churn) are first-class fields
instead of hardcoded constants.
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass
from typing import Optional

#: Index (0-based) of the introducer/coordinator peer.  The reference
#: hardwires the join address to id=1:port=0 (Application.cpp:209-217,
#: MP1Node.cpp:378-386); ids are assigned sequentially from 1
#: (EmulNet.cpp:72-77), so the introducer is always peer index 0.
INTRODUCER = 0


@dataclass(frozen=True)
class SimConfig:
    """All parameters of one simulation scenario.

    Field names follow the reference's .conf keys where they exist
    (Params.cpp:22-25); the rest mirror the reference's compile-time
    constants with the same defaults.
    """

    # --- legacy .conf fields (Params.cpp:22-25) ---
    max_nnb: int = 10            # MAX_NNB -> number of peers (EN_GPSZ = MAX_NNB, Params.cpp:29)
    single_failure: bool = True  # SINGLE_FAILURE
    drop_msg: bool = False       # DROP_MSG
    msg_drop_prob: float = 0.1   # MSG_DROP_PROB

    # --- reference compile-time constants ---
    total_ticks: int = 700       # TOTAL_RUNNING_TIME (Application.h:27)
    step_rate: float = 0.25      # Params.cpp:30; node i starts at int(step_rate*i)
    t_remove: int = 20           # TREMOVE (MP1Node.h:21)
    t_fail: int = 5              # TFAIL (MP1Node.h:22) — vestigial in the reference too
    portnum: int = 8001          # Params.cpp:12 — note ENinit still assigns port 0
    max_msg_size: int = 4000     # Params.cpp:31
    en_buff_size: int = 30000    # ENBUFFSIZE (EmulNet.h:12)
    fail_tick: int = 100         # failure injection time (Application.cpp:181,188)
    drop_open_tick: int = 50     # drop window opens (Application.cpp:177)
    drop_close_tick: int = 300   # drop window closes (Application.cpp:198)

    # --- new framework knobs (absent in the reference) ---
    #: PRNG seed.  The reference uses ``srand(time(NULL))`` twice
    #: (Application.cpp:50,96) so its runs are irreproducible; we default
    #: to a fixed seed and treat reproducibility as a feature.
    seed: int = 0
    #: Protocol/model family: "full_view" reproduces the reference's
    #: all-pairs full-list heartbeating; "overlay" is the bounded
    #: partial-view family for very large N (BASELINE.json 65k/1M configs).
    model: str = "full_view"
    #: Overlay exchange fanout (only used by model="overlay");
    #: 0 = auto (~log2(N)/2 + 2, see models/overlay.py resolved_dims).
    fanout: int = 0
    #: Overlay view capacity K (slots per node; models/overlay.py).
    #: 0 = auto (~4*log2 N, capped at 64).  Right-sizing matters: too
    #: large a view at small N starves slots of merge candidates.
    overlay_view: int = 0
    #: Overlay payload sample L: view slots carried per message
    #: (rotating window; full view every K/L ticks).  0 = auto (K/2).
    overlay_sample: int = 0
    #: Exchange-graph degree family (overlay only).  "uniform": every
    #: node gossips on all F rounds each tick (Erdős–Rényi-flavored —
    #: the BASELINE 65k shape).  "powerlaw": per-node out-degrees
    #: follow a bounded Pareto tail (P[deg >= k] ~ k^-(alpha-1), the
    #: BASELINE 1M scale-free shape): a few hubs gossip on many rounds,
    #: most nodes on few.  Degrees are a static seeded node property.
    topology: str = "uniform"
    #: Pareto tail exponent for topology="powerlaw".
    powerlaw_alpha: float = 2.5
    #: Churn rate per tick (overlay extension; 0 disables).
    churn_rate: float = 0.0
    #: Churn/rejoin extension (SURVEY.md §5 — the reference never
    #: re-admits a failed node): failed peers are wiped and re-introduced
    #: ``rejoin_after`` ticks after their failure, rejoining through the
    #: normal JOINREQ path.  None disables (reference behavior).
    rejoin_after: Optional[int] = None

    # --- adversarial failure worlds (worlds.py; closed-form
    # --- (seed, tick, node) draws shared by both models) ---
    #: Network partition: >= 2 hashes every node into that many
    #: groups; cross-group sends are blocked while the window below is
    #: open (heals when it closes).  0 disables.
    partition_groups: int = 0
    #: Partition window: cross-group sends blocked for
    #: ``open < t <= close`` (the drop-window convention).
    partition_open_tick: int = 0
    partition_close_tick: int = 0
    #: Asymmetric per-link drop: replaces the uniform ``msg_drop_prob``
    #: with a hashed per-(sender, receiver) threshold of mean
    #: ``msg_drop_prob`` (max ~2x), active during the drop window.
    asym_drop: bool = False
    #: Correlated failure wave: > 0 fails that many nodes in the
    #: contiguous ring block from a seeded epicenter, one radius step
    #: per ``wave_speed`` ticks from ``wave_tick`` (-1: ``fail_tick``).
    #: Replaces the scripted single/multi failure, like churn does.
    wave_size: int = 0
    wave_tick: int = -1
    wave_speed: int = 1
    #: Zombie / stale-table peers: window-failed peers keep gossiping
    #: their frozen table (and frozen heartbeat) instead of going
    #: silent — the false-positive stress world.
    zombie: bool = False
    #: Flapping members: > 0 selects that fraction of nodes to fail and
    #: rejoin periodically inside ``[flap_open, flap_close]`` with a
    #: closed-form duty cycle (down ``flap_down`` of every
    #: ``flap_period`` ticks; -1 windows default to the churn
    #: machinery's quarter points).
    flap_rate: float = 0.0
    flap_period: int = 32
    flap_down: int = 8
    flap_open_tick: int = -1
    flap_close_tick: int = -1
    #: Byzantine forgery plane (round 2): > 0 selects that fraction of
    #: nodes as seeded liars (introducer exempt).  Liars inflate their
    #: own heartbeat counter, relay their table at forged freshness
    #: with heartbeats inflated by ``byz_boost``, and advertise a
    #: hashed set of ghost members they have never heard from.  The
    #: direct-sender-credit defense (liveness evidence is direct-only)
    #: compiles in with the plane — see worlds.py.
    byz_rate: float = 0.0
    byz_boost: int = 8
    #: Per-link latency plane (round 2): maximum EXTRA delivery delay
    #: in ticks.  Link (i -> j) delivers gossip after
    #: ``1 + mix32(seed, i*n+j, SALT_LAT) % (link_latency + 1)`` ticks
    #: (same hashed-link construction as asym_drop); 0 disables —
    #: every link keeps the reference's one-tick delivery.  Applies to
    #: gossip only (the introducer join path stays one-tick, so the
    #: segment planner's join windows are untouched).
    link_latency: int = 0

    def __post_init__(self):
        if self.model == "overlay":
            n = self.max_nnb
            if n < 4 or n & (n - 1) != 0:
                lo = 1 << max(2, n.bit_length() - 1)
                hi = max(4, 1 << n.bit_length())
                near = lo if (n - lo) <= (hi - n) else hi
                raise ValueError(
                    f"overlay peer count must be a power of two >= 4 "
                    f"(the XOR partner exchange pairs node i with "
                    f"i ^ mask over a 2^b address space), got n={n}; "
                    f"nearest valid n is {near} (or {lo}/{hi})")
        if self.partition_groups == 1 or self.partition_groups < 0:
            raise ValueError(
                f"partition_groups must be 0 (off) or >= 2, got "
                f"{self.partition_groups}")
        if self.partition_groups >= 2:
            if self.partition_close_tick <= self.partition_open_tick:
                raise ValueError(
                    f"partition window ({self.partition_open_tick}, "
                    f"{self.partition_close_tick}] is empty; close must "
                    "exceed open")
            # a window that opens after the run ends silently never
            # engages (same early-failure rule as the flap window;
            # close past the end is legal — "never heals")
            if self.partition_open_tick >= self.total_ticks:
                raise ValueError(
                    f"partition opens at tick "
                    f"{self.partition_open_tick}, after the run ends "
                    f"at {self.total_ticks} — the world would never "
                    "engage")
        if self.asym_drop:
            if not self.drop_msg:
                raise ValueError(
                    "asym_drop rides the drop window; set drop_msg=True")
            if not 0.0 < self.msg_drop_prob < 0.5:
                raise ValueError(
                    f"asym_drop needs 0 < msg_drop_prob < 0.5 (per-link "
                    f"probabilities reach 2x the mean), got "
                    f"{self.msg_drop_prob}")
        if self.wave_size < 0:
            raise ValueError(f"wave_size must be >= 0, got {self.wave_size}")
        if self.wave_size > 0:
            if self.wave_speed < 1:
                raise ValueError(
                    f"wave_speed must be >= 1, got {self.wave_speed}")
            if self.churn_rate > 0:
                raise ValueError(
                    "wave_size and churn_rate both replace the scripted "
                    "failure; enable at most one")
            start = self.fail_tick if self.wave_tick < 0 else self.wave_tick
            if start >= self.total_ticks:
                raise ValueError(
                    f"wave epicenter fails at tick {start}, after the "
                    f"run ends at {self.total_ticks} — the world would "
                    "never engage")
        if self.flap_rate < 0 or self.flap_rate > 1:
            raise ValueError(
                f"flap_rate must be in [0, 1], got {self.flap_rate}")
        if self.flap_rate > 0:
            if not 1 <= self.flap_down < self.flap_period:
                raise ValueError(
                    f"flapping needs 1 <= flap_down < flap_period, got "
                    f"down={self.flap_down} period={self.flap_period}")
            # the resolved window must admit at least one completable
            # cycle (anchor = flap_open in the best case), or the
            # world silently never engages — fail early instead
            lo = self.total_ticks // 4 if self.flap_open_tick < 0 \
                else self.flap_open_tick
            hi = (3 * self.total_ticks) // 4 if self.flap_close_tick < 0 \
                else self.flap_close_tick
            if lo + self.flap_down > hi:
                raise ValueError(
                    f"flap window [{lo}, {hi}] cannot complete a "
                    f"single down phase of {self.flap_down} ticks — "
                    "no node would ever flap; widen the window or "
                    "shrink flap_down")
        if self.byz_rate < 0 or self.byz_rate > 1:
            raise ValueError(
                f"byz_rate must be in [0, 1], got {self.byz_rate}")
        if self.byz_rate > 0 and self.byz_boost < 1:
            raise ValueError(
                f"the Byzantine plane needs byz_boost >= 1 (a 0-boost "
                f"liar forges nothing), got {self.byz_boost}")
        if self.link_latency < 0 or self.link_latency > 23:
            # delays draw in [1, link_latency + 1], so 23 caps the
            # overlay's send-history bitmask at 24 bits — f32 is exact
            # only for integers below 2^24, and the history word rides
            # the f32 permutation matmuls
            raise ValueError(
                f"link_latency must be in [0, 23] ticks, got "
                f"{self.link_latency}")
        if self.link_latency > 0 \
                and self.link_latency + 1 >= self.t_remove:
            raise ValueError(
                f"link_latency={self.link_latency} reaches the "
                f"staleness horizon t_remove={self.t_remove}: a clean "
                "slow link would manufacture false removals; keep "
                "link_latency + 1 < t_remove")

    def worlds_key(self) -> tuple:
        """Hashable digest of the ACTIVE adversarial worlds — the
        static-branch knobs a compiled tick bakes in.  Empty for the
        course worlds; folded into the dense fleet shape key, the
        run-cache keys, and the kernel support gates (the Pallas
        mega/grid kernels do not compile the new worlds — world
        configs take the XLA paths).

        This is the EXACT key: it pins every world parameter, which
        is what the solo run cache and checkpoint-leg validation
        need.  The serving layer's canonical tier keeps only the
        plane TAGS and moves the parameters to runtime operands
        (worlds.canonical_world_key / OPERAND_WORLD_FIELDS, PR 16) —
        a change here must be mirrored there or the canonical
        completeness pass (``canon-key-complete``) will name the
        uncovered field."""
        ws = []
        if self.partition_groups >= 2:
            ws.append(("part", self.partition_groups,
                       self.partition_open_tick,
                       self.partition_close_tick))
        if self.asym_drop:
            ws.append(("asym",))
        if self.wave_size > 0:
            ws.append(("wave", self.wave_size, self.wave_tick,
                       self.wave_speed))
        if self.zombie:
            ws.append(("zombie",))
        if self.flap_rate > 0:
            ws.append(("flap", self.flap_rate, self.flap_period,
                       self.flap_down, self.flap_open_tick,
                       self.flap_close_tick))
        if self.byz_rate > 0:
            ws.append(("byz", self.byz_rate, self.byz_boost))
        if self.link_latency > 0:
            ws.append(("lat", self.link_latency))
        return tuple(ws)

    @property
    def has_worlds(self) -> bool:
        return bool(self.worlds_key())

    @property
    def has_latency(self) -> bool:
        """The per-link latency plane is on (kernel gates check this
        explicitly, though ``lat`` in :meth:`worlds_key` already routes
        latency configs off every fused path via ``has_worlds``)."""
        return self.link_latency > 0

    @property
    def n(self) -> int:
        """Number of peers (the reference's EN_GPSZ, Params.cpp:29)."""
        return self.max_nnb

    def start_tick(self, i: int) -> int:
        """Tick at which peer index ``i`` is introduced.

        Reference: nodes start when ``t == (int)(STEP_RATE*i)``
        (Application.cpp:143), i.e. C truncation of 0.25*i.
        """
        return int(self.step_rate * i)

    def replace(self, **kw) -> "SimConfig":
        return dataclasses.replace(self, **kw)

    # --- journal serialization (store/journal.py) -------------------
    def to_dict(self) -> dict:
        """JSON-ready field dict.

        Every field is an int/float/bool/str/None scalar, so
        ``json.dumps(cfg.to_dict())`` round-trips exactly (Python's
        float repr is lossless) — the write-ahead journal and the
        spilled-checkpoint headers (gossip_protocol_tpu/store/) both
        persist configs this way and must get back an ``==`` config.
        """
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SimConfig":
        """Inverse of :meth:`to_dict`.

        Unknown keys are dropped rather than rejected so a journal
        written by a NEWER config schema still replays on an older
        one (the surviving fields keep their recorded values; missing
        fields take defaults) — recovery re-validates results by
        digest, so a semantic mismatch fails loudly downstream
        instead of here.
        """
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    # --- legacy .conf ingestion -------------------------------------
    @classmethod
    def from_conf(cls, path: str, **overrides) -> "SimConfig":
        """Parse a reference-format .conf file (Params.cpp:22-25).

        The reference reads exactly four ``KEY: value`` lines in fixed
        order with fscanf; we accept them in any order and ignore
        unknown keys, but the three shipped testcases parse bit-identically.
        """
        keys = {}
        with open(path, "r") as f:
            for line in f:
                m = re.match(r"\s*([A-Z_]+)\s*:\s*([0-9.eE+-]+)", line)
                if m:
                    keys[m.group(1)] = m.group(2)
        if "MAX_NNB" not in keys and "max_nnb" not in overrides:
            # A conf that never mentions MAX_NNB is malformed or
            # mis-pathed (the reference's positional fscanf would read
            # garbage, Params.cpp:22-25); refuse to silently simulate
            # the defaults.  native/params.cc applies the same rule.
            raise ValueError(f"no MAX_NNB key in {path}")
        kw = {}
        if "MAX_NNB" in keys:
            kw["max_nnb"] = int(keys["MAX_NNB"])
        if "SINGLE_FAILURE" in keys:
            kw["single_failure"] = bool(int(keys["SINGLE_FAILURE"]))
        if "DROP_MSG" in keys:
            kw["drop_msg"] = bool(int(keys["DROP_MSG"]))
        if "MSG_DROP_PROB" in keys:
            kw["msg_drop_prob"] = float(keys["MSG_DROP_PROB"])
        kw.update(overrides)
        return cls(**kw)


#: The three scenarios shipped with the reference (testcases/*.conf).
SINGLE_FAILURE = SimConfig(max_nnb=10, single_failure=True, drop_msg=False)
MULTI_FAILURE = SimConfig(max_nnb=10, single_failure=False, drop_msg=False)
MSG_DROP_SINGLE_FAILURE = SimConfig(max_nnb=10, single_failure=True, drop_msg=True,
                                    msg_drop_prob=0.1)
