"""Pallas TPU kernel for the fused gossip-merge reduction.

Computes the same three product-max reductions as
``ops.merge.gossip_reductions`` — the (max, and) semiring "matmul" that
replaces the reference's per-message linear-scan merge
(MP1Node.cpp:236-256) — in one fused pass:

    m_a[r, j] = max_s  d[r, s] * a1[s, j]     (a1 = known ? hb+1 : 0)
    m_f, m_t  = ditto over the fresh payload planes f1 / t1

Grid is (R/TR, J/TJ, S/TS) with the sender axis innermost; each program
max-accumulates its (TR, TJ) output tiles in VMEM across sender tiles,
so the O(R*S*J) semiring contraction never round-trips HBM between
sender blocks.  Inside a tile the sender axis is consumed in sublane
chunks of 8 (the VPU's sublane width for 32-bit lanes): each chunk is a
(TR, 8) x (8, TJ) broadcast-multiply-max — two VPU ops per cell per
reduction, with the (TR, 8, TJ) intermediate living entirely in
registers/VMEM.

The public wrapper pads arbitrary shapes up to tile multiples (padded
delivery rows are all-zero, so they contribute nothing) and accepts the
same dtypes as the XLA-path op.  ``interpret=True`` is used
automatically off-TPU so the kernel is testable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..merge import merge_payloads

_SUB = 8  # sender sublane chunk


def _kernel(tr_tile: int,
            d_ref, a1_ref, f1_ref, t1_ref,
            m_a_ref, m_f_ref, m_t_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        m_a_ref[:] = jnp.zeros_like(m_a_ref)
        m_f_ref[:] = jnp.zeros_like(m_f_ref)
        m_t_ref[:] = jnp.zeros_like(m_t_ref)

    d = d_ref[:]                # (TR, TS) int32 0/1
    a1 = a1_ref[:]              # (TS, TJ)
    f1 = f1_ref[:]
    t1 = t1_ref[:]

    # Receiver axis in static sublane chunks: every slice below is
    # sublane-aligned (lane-dimension slicing at non-128 offsets does
    # not lower on Mosaic, and slice+newaxis in one indexing expression
    # lowers via gather — hence the explicit expand_dims), and the
    # (8, TS, TJ) broadcast-product keeps the sender axis on sublanes
    # where the max-reduce is native.
    a1x = jnp.expand_dims(a1, 0)                     # (1, TS, TJ)
    f1x = jnp.expand_dims(f1, 0)
    t1x = jnp.expand_dims(t1, 0)
    for r0 in range(0, tr_tile, _SUB):
        dx = jnp.expand_dims(d[r0:r0 + _SUB, :], 2)  # (8, TS, 1)
        m_a_ref[r0:r0 + _SUB, :] = jnp.maximum(
            m_a_ref[r0:r0 + _SUB, :], (dx * a1x).max(1))
        m_f_ref[r0:r0 + _SUB, :] = jnp.maximum(
            m_f_ref[r0:r0 + _SUB, :], (dx * f1x).max(1))
        m_t_ref[r0:r0 + _SUB, :] = jnp.maximum(
            m_t_ref[r0:r0 + _SUB, :], (dx * t1x).max(1))


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


@functools.partial(jax.jit, static_argnames=("t_remove", "tile_r", "tile_j",
                                             "tile_s", "interpret"))
def gossip_reductions_pallas(recv_from, known, hb, ts, now, *,
                             t_remove: int, tile_r: int = 128,
                             tile_j: int = 128, tile_s: int = 128,
                             interpret: bool | None = None):
    """Drop-in Pallas implementation of ``ops.merge.gossip_reductions``.

    Arbitrary shapes are padded up to tile multiples; padded rows and
    columns are sliced back off the outputs.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    r_dim, s_dim = recv_from.shape
    j_dim = known.shape[1]

    a1, f1, t1 = merge_payloads(known, hb, ts, now, t_remove)
    d = recv_from.astype(jnp.int32)

    tr = min(tile_r, _ceil_to(r_dim, _SUB))
    tj = min(tile_j, _ceil_to(j_dim, 128))
    tss = min(tile_s, _ceil_to(s_dim, _SUB))
    rp, jp, sp = _ceil_to(r_dim, tr), _ceil_to(j_dim, tj), _ceil_to(s_dim, tss)
    if (rp, sp) != (r_dim, s_dim):
        d = jnp.pad(d, ((0, rp - r_dim), (0, sp - s_dim)))
    if (sp, jp) != (s_dim, j_dim):
        pad = ((0, sp - s_dim), (0, jp - j_dim))
        a1, f1, t1 = jnp.pad(a1, pad), jnp.pad(f1, pad), jnp.pad(t1, pad)

    grid = (rp // tr, jp // tj, sp // tss)
    out_shape = [jax.ShapeDtypeStruct((rp, jp), jnp.int32)] * 3
    out_spec = pl.BlockSpec((tr, tj), lambda i, j, k: (i, j),
                            memory_space=pltpu.VMEM)

    m_a, m_f, m_t = pl.pallas_call(
        functools.partial(_kernel, tr),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tr, tss), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),               # d
            pl.BlockSpec((tss, tj), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),               # a1
            pl.BlockSpec((tss, tj), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),               # f1
            pl.BlockSpec((tss, tj), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),               # t1
        ],
        out_specs=[out_spec] * 3,
        out_shape=out_shape,
        interpret=interpret,
    )(d, a1, f1, t1)

    if (rp, jp) != (r_dim, j_dim):
        m_a = m_a[:r_dim, :j_dim]
        m_f = m_f[:r_dim, :j_dim]
        m_t = m_t[:r_dim, :j_dim]
    return m_a - 1, m_f - 1, m_t - 1, m_t > 0
