"""Pallas TPU kernel for the fused gossip-merge reduction.

Computes the same four maxima as ``ops.merge.gossip_reductions`` — the
(max, and) semiring "matmul" that replaces the reference's per-message
linear-scan merge (MP1Node.cpp:236-256) — in one fused pass:

    m_all[r, j]  = max_s { hb[s, j] : recv[r, s] & known[s, j] }
    m_fr / t_fr  = ditto restricted to fresh entries (now - ts < TREMOVE)
    anyf[r, j]   = fresh contribution exists

Grid is (R/TR, J/TJ, S/TS) with the sender axis innermost; each program
max-accumulates its (TR, TJ) output tile in VMEM across sender tiles,
so the O(R*S*J) semiring contraction never round-trips HBM between
sender blocks.  Inside a tile the sender axis is consumed in sublane
chunks of 8 (the VPU's sublane width for 32-bit lanes), keeping the 3-D
broadcast intermediate at (TR, 8, TJ).

Masks travel as int32 0/1 (TPU-friendly tiling); the public wrapper
accepts/returns the same dtypes as the XLA-path op.  ``interpret=True``
is used automatically off-TPU so the kernel is testable on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..merge import FILL

_SUB = 8  # sender sublane chunk


def _kernel(t_remove: int, ts_tile: int,
            now_ref, recv_ref, known_ref, hb_ref, ts_ref,
            m_all_ref, m_fr_ref, t_fr_ref, anyf_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        m_all_ref[:] = jnp.full_like(m_all_ref, FILL)
        m_fr_ref[:] = jnp.full_like(m_fr_ref, FILL)
        t_fr_ref[:] = jnp.full_like(t_fr_ref, FILL)
        anyf_ref[:] = jnp.zeros_like(anyf_ref)

    now = now_ref[0]
    recv = recv_ref[:]          # (TR, TS) int32 0/1
    known = known_ref[:]        # (TS, TJ)
    hb = hb_ref[:]
    ts = ts_ref[:]
    fresh_row = (now - ts < t_remove)  # (TS, TJ) bool

    m_all = m_all_ref[:]
    m_fr = m_fr_ref[:]
    t_fr = t_fr_ref[:]
    anyf = anyf_ref[:]

    for s0 in range(0, ts_tile, _SUB):
        d8 = recv[:, s0:s0 + _SUB] > 0                    # (TR, 8)
        k8 = known[s0:s0 + _SUB] > 0                      # (8, TJ)
        contrib = d8[:, :, None] & k8[None]               # (TR, 8, TJ)
        hb8 = hb[s0:s0 + _SUB][None]
        m_all = jnp.maximum(m_all, jnp.where(contrib, hb8, FILL).max(1))
        fresh = contrib & fresh_row[s0:s0 + _SUB][None]
        m_fr = jnp.maximum(m_fr, jnp.where(fresh, hb8, FILL).max(1))
        t_fr = jnp.maximum(t_fr,
                           jnp.where(fresh, ts[s0:s0 + _SUB][None], FILL).max(1))
        anyf = anyf | fresh.any(1).astype(jnp.int32)

    m_all_ref[:] = m_all
    m_fr_ref[:] = m_fr
    t_fr_ref[:] = t_fr
    anyf_ref[:] = anyf


@functools.partial(jax.jit, static_argnames=("t_remove", "tile_r", "tile_j",
                                             "tile_s", "interpret"))
def gossip_reductions_pallas(recv_from, known, hb, ts, now, *,
                             t_remove: int, tile_r: int = 128,
                             tile_j: int = 128, tile_s: int = 128,
                             interpret: bool | None = None):
    """Drop-in Pallas implementation of ``ops.merge.gossip_reductions``.

    Shapes must tile evenly (pad at the call site if needed; the tick
    path uses power-of-two N for the dense model).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    r_dim, s_dim = recv_from.shape
    j_dim = known.shape[1]
    tr = min(tile_r, r_dim)
    tj = min(tile_j, j_dim)
    tss = min(tile_s, s_dim)
    assert r_dim % tr == 0 and j_dim % tj == 0 and s_dim % tss == 0 \
        and tss % _SUB == 0, (r_dim, s_dim, j_dim, tr, tj, tss)

    grid = (r_dim // tr, j_dim // tj, s_dim // tss)
    out_shape = [jax.ShapeDtypeStruct((r_dim, j_dim), jnp.int32)] * 4
    out_spec = pl.BlockSpec((tr, tj), lambda i, j, k: (i, j),
                            memory_space=pltpu.VMEM)

    m_all, m_fr, t_fr, anyf = pl.pallas_call(
        functools.partial(_kernel, t_remove, tss),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),               # now
            pl.BlockSpec((tr, tss), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),               # recv_from
            pl.BlockSpec((tss, tj), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),               # known
            pl.BlockSpec((tss, tj), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),               # hb
            pl.BlockSpec((tss, tj), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),               # ts
        ],
        out_specs=[out_spec] * 4,
        out_shape=out_shape,
        interpret=interpret,
    )(jnp.asarray([now], jnp.int32),
      recv_from.astype(jnp.int32), known.astype(jnp.int32),
      hb.astype(jnp.int32), ts.astype(jnp.int32))

    return m_all, m_fr, t_fr, anyf.astype(bool)
