"""Multi-tick Pallas megakernel for the DENSE full-view model.

The dense reference-faithful tick (core/tick.py) pays the same fixed
per-launch + per-dispatch floor the overlay paid before its megakernel
(docs/PERF.md): at N=512 the whole tick is ~0.5 ms of which the
useful math — the MXU level-decomposed merge plus (N, N) elementwise
rules — is tens of microseconds.  This kernel runs ``DENSE_MEGA_TICKS``
whole dense ticks per launch with the full world state resident in
VMEM: the four (N, N) planes (known, hb, ts, gossip), the per-peer
vectors, and the schedule columns.

Everything from core/tick.py's composable path runs in-kernel, in the
same order and with the same jnp formulas (bit-parity is the contract;
tests/test_dense_mega.py runs the differential suite):

* phase A — consume in-flight traffic: ``deliver = gossip & proc``,
  one (N, N) transpose for ``recv_from`` (MP1Node.cpp:200-209 analog);
* the gossip piggyback merge (MP1Node.cpp:244-256) as the same masked
  max-over-senders used by ops/merge.py ``_masked_max_mxu``: a
  level-descend ``lax.while_loop`` whose (N, N) state lives in VMEM
  scratch refs with a scalar-only carry (Mosaic cannot legalize
  vector-carried ``scf.while``) and whose witness resolution is one
  s8 x s8 -> s32 MXU matmul per level — exact (operands are 0/1,
  accumulation is s32) at 2x the bf16 MXU rate, verified on-chip;
* direct-sender increment / add (MP1Node.cpp:236-242), JOINREQ at the
  introducer (MP1Node.cpp:221-230), JOINREP at the joiner
  (MP1Node.cpp:231-233), TREMOVE staleness detection
  (MP1Node.cpp:339-348), full-list dissemination (MP1Node.cpp:350-361)
  and the sent/recv accounting rows (EmulNet.cpp:111,172).

Drop decisions are NOT derived in-kernel: the dense model's drop masks
come from ``jax.random`` (ops/drop.py); the harness precomputes the
per-tick masks for the whole launch outside and passes them as inputs,
so kernel and XLA paths consume byte-identical randomness.

Scope: single device, N <= DENSE_MEGA_N_LIMIT (VMEM: ~12 live (N, N)
i32 planes plus the (S, N, N) drop stack).  ``with_events`` adds the
grader-visible added/removed masks as (S, N, N) int8 outputs written
per tick in-kernel (~4 MB each at N=512) — the graded trace-mode run
(dbg.log events for every add/remove, /root/reference/Log.cpp:97-131)
rides the same megakernel as the bench path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tpu_compiler_params

#: dense ticks per launch (halved above 512 peers: the (S, N, N) drop
#: stack and the ~12 live (N, N) temporaries share the same VMEM)
DENSE_MEGA_TICKS = 16

#: VMEM bound: ~(8 + S/4 + ~12 temporaries) (N, N) i32-equivalent
#: planes must fit under the raised scoped window.  Bench mode (no
#: event outputs) is hardware-validated up to 1024 (the active-corner
#: width of the BASELINE N=4096 dense config is 896); trace mode adds
#: two (S, N, N) event planes and keeps the 512 envelope.
DENSE_MEGA_N_LIMIT = 512
DENSE_MEGA_N_LIMIT_BENCH = 1024


def dense_mega_ticks_for(n: int) -> int:
    """Ticks per launch for a peer count (VMEM-bounded)."""
    return DENSE_MEGA_TICKS if n <= DENSE_MEGA_N_LIMIT \
        else DENSE_MEGA_TICKS // 2

#: aux lane offsets
_IN_GROUP = 0
_OWN_HB = 1
_JOINREQ = 2
_JOINREP = 3
_START = 4
_FAIL = 5
_REJOIN = 6
DENSE_AUX_LANES = 8

_SP_T0 = 0


def _kernel(n: int, s_ticks: int, t_remove: int, can_rejoin: bool,
            with_events: bool,
            sp_ref,
            known_in, hb_in, ts_in, gossip_in, aux_in,
            gdrop_ref, qdrop_ref, pdrop_ref,
            known_o, hb_o, ts_o, gossip_o, aux_o, sent_o, recv_o,
            *evrefs_and_scr):
    if with_events:
        added_o, removed_o = evrefs_and_scr[:2]
        m_scr, done_scr, cur_scr = evrefs_and_scr[2:]
    else:
        m_scr, done_scr, cur_scr = evrefs_and_scr
    from ...config import INTRODUCER

    i32 = jnp.int32
    rows = jax.lax.broadcasted_iota(i32, (n, 1), 0)
    cols = jax.lax.broadcasted_iota(i32, (1, n), 1)
    self_mask = jax.lax.broadcasted_iota(i32, (n, n), 0) \
        == jax.lax.broadcasted_iota(i32, (n, n), 1)
    is_intro = rows == INTRODUCER          # (N, 1)
    intro_col = cols == INTRODUCER         # (1, N)

    known_o[:] = known_in[:]
    hb_o[:] = hb_in[:]
    ts_o[:] = ts_in[:]
    gossip_o[:] = gossip_in[:]
    aux_o[:] = aux_in[:]

    def masked_max(d_i8, v):
        """m[r, j] = max over senders s with d[r, s] of v[s, j]
        (0 if none) — ops/merge.py _masked_max_mxu ported to scratch
        refs + scalar-carried while (see module docstring).  Witness
        matmuls run s8 x s8 -> s32 (2x the bf16 MXU rate, exact)."""
        m_scr[:] = jnp.zeros((n, n), i32)
        done_scr[:] = jnp.zeros((n, n), i32)
        cur_scr[0:1, :] = v.max(axis=0, keepdims=True)

        def cond(go):
            return go

        def body(go):
            cur = cur_scr[0:1, :]
            w = ((v == cur) & (cur > 0)).astype(jnp.int8)
            hit = jax.lax.dot_general(
                d_i8, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32) > 0
            done = done_scr[:] > 0
            newly = hit & ~done
            m_scr[:] = jnp.where(newly, jnp.broadcast_to(cur, (n, n)),
                                 m_scr[:])
            done = done | newly | jnp.broadcast_to(cur == 0, (n, n))
            done_scr[:] = done.astype(i32)
            nxt = jnp.where(v < cur, v, 0).max(axis=0, keepdims=True)
            cur_scr[0:1, :] = nxt
            return (~done).any() & (nxt > 0).any()

        jax.lax.while_loop(cond, body, jnp.asarray(True))
        return m_scr[:]

    def tick(s, _):
        t = sp_ref[_SP_T0] + s
        aux = aux_o[:]
        in_group0 = aux[:, _IN_GROUP:_IN_GROUP + 1] > 0
        own_hb0 = aux[:, _OWN_HB:_OWN_HB + 1]
        joinreq0 = aux[:, _JOINREQ:_JOINREQ + 1] > 0
        joinrep0 = aux[:, _JOINREP:_JOINREP + 1] > 0
        start = aux[:, _START:_START + 1]
        fail = aux[:, _FAIL:_FAIL + 1]
        rejoin = aux[:, _REJOIN:_REJOIN + 1]

        failed = (t > fail) & (t <= rejoin)
        proc = (t > start) & ~failed                     # (N, 1)

        # ---- churn wipe (core/tick.py rejoin re-init) --------------
        if can_rejoin:
            rejoining = t == rejoin
            keep = (~rejoining).astype(i32)
            known_o[:] = known_o[:] * keep
            hb_o[:] = hb_o[:] * keep
            ts_o[:] = ts_o[:] * keep
            in_group0 = in_group0 & ~rejoining
            own_hb0 = own_hb0 * keep
        else:
            rejoining = jnp.zeros_like(is_intro)

        # introducer gates as (1, 1) broadcastable scalars
        start0 = aux[INTRODUCER:INTRODUCER + 1, _START:_START + 1]
        fail0 = aux[INTRODUCER:INTRODUCER + 1, _FAIL:_FAIL + 1]
        rejoin0 = aux[INTRODUCER:INTRODUCER + 1, _REJOIN:_REJOIN + 1]
        failed0 = (t > fail0) & (t <= rejoin0)
        proc0 = (t > start0) & ~failed0                  # (1, 1)

        known_b = known_o[:] > 0
        hb0 = hb_o[:]
        ts0 = ts_o[:]
        gossip_b = gossip_o[:] > 0

        # ---- phase A: consume in-flight traffic --------------------
        proc_t = jnp.transpose(proc.astype(i32)) > 0     # (1, N)
        deliver = gossip_b & proc_t                      # [s, r]
        jreq = joinreq0 & proc0                          # (N, 1)
        jrep = joinrep0 & proc                           # (N, 1)
        recv_from = jnp.transpose(deliver.astype(i32)) > 0   # [r, s]

        # ---- nodeStart + per-tick vector decisions -----------------
        starting = (t == start) | rejoining
        joinreq_new = starting & ~is_intro
        in_group = in_group0 | jrep | (starting & is_intro)
        ops = proc & in_group                            # (N, 1)
        own_hb = own_hb0 + ops.astype(i32)

        gdrop = gdrop_ref[pl.ds(s, 1)].reshape(n, n)     # bool [s, r]
        # dynamic slicing must ride the SUBLANE axis (lane-dynamic
        # offsets need a static multiple-of-128 proof in Mosaic), so
        # the per-tick vectors are stored (S, N) and transposed here
        qdrop = jnp.transpose(
            qdrop_ref[pl.ds(s, 1), :].astype(i32)) > 0   # (N, 1)
        pdrop = jnp.transpose(
            pdrop_ref[pl.ds(s, 1), :].astype(i32)) > 0
        joinreq_sent = joinreq_new & ~qdrop
        joinrep_sent = jreq & ~pdrop
        live_hold = ~proc & ~failed                      # (N, 1)

        # ---- piggyback merge (ops/merge.py contract) ---------------
        k_i = known_b.astype(i32)
        fresh = k_i * (t - ts0 < t_remove)
        d_i8 = recv_from.astype(jnp.int8)
        m_a = masked_max(d_i8, k_i * (hb0 + 1)) - 1
        m_f = masked_max(d_i8, fresh * (hb0 + 1)) - 1
        m_t = masked_max(d_i8, fresh * (ts0 + 1)) - 1
        any_fresh = m_t >= 0

        exists = known_b
        inc = exists & (m_a > hb0)
        hb = jnp.where(inc, m_a, hb0)
        ts = jnp.where(inc, t, ts0)
        padd = ~exists & any_fresh & ~self_mask
        hb = jnp.where(padd, m_a, hb)
        ts = jnp.where(padd, jnp.where(m_a > m_f, t, m_t), ts)

        # ---- direct-sender handling --------------------------------
        known_pb = exists | padd
        dinc = recv_from & known_pb
        hb = jnp.where(dinc, hb + 1, hb)
        ts = jnp.where(dinc, t, ts)
        dadd = recv_from & ~known_pb & ~self_mask
        hb = jnp.where(dadd, 1, hb)
        ts = jnp.where(dadd, t, ts)
        known = exists | padd | dadd

        # ---- JOINREQ at the introducer -----------------------------
        intro_row = known[INTRODUCER:INTRODUCER + 1, :]  # (1, N)
        jreq_t = jnp.transpose(jreq.astype(i32)) > 0     # (1, N)
        qadd = jreq_t & ~intro_row & ~intro_col
        q_cell = is_intro & qadd                         # (N, N)
        known = known | q_cell
        hb = jnp.where(q_cell, 1, hb)
        ts = jnp.where(q_cell, t, ts)

        # ---- JOINREP at the joiner ---------------------------------
        radd = jrep & ~known[:, INTRODUCER:INTRODUCER + 1]
        r_cell = radd & intro_col
        known = known | r_cell
        hb = jnp.where(r_cell, 1, hb)
        ts = jnp.where(r_cell, t, ts)

        # ---- detection + dissemination -----------------------------
        stale = ops & known & (t - ts >= t_remove)
        if with_events:
            # grader-visible masks (core/tick.py TickEvents): adds are
            # judged against the post-wipe start-of-tick membership,
            # removals are the staleness mask
            added_o[pl.ds(s, 1), :, :] = \
                (known & ~known_b).astype(jnp.int8).reshape(1, n, n)
            removed_o[pl.ds(s, 1), :, :] = \
                stale.astype(jnp.int8).reshape(1, n, n)
        known = known & ~stale
        send = ops & known
        gossip_sent = send & ~gdrop
        live_hold_t = jnp.transpose(live_hold.astype(i32)) > 0   # (1, N)
        gossip_next = gossip_sent | (gossip_b & live_hold_t)
        joinreq_next = joinreq_sent | (joinreq0 & ~proc0 & ~failed0)
        joinrep_next = joinrep_sent | (joinrep0 & live_hold)

        # ---- accounting (EmulNet.cpp:111,172) ----------------------
        rep_total = joinrep_sent.astype(i32).sum(0, keepdims=True) \
            .sum(1, keepdims=True)                       # (1, 1)
        req_total = jreq.astype(i32).sum(0, keepdims=True) \
            .sum(1, keepdims=True)
        sent_row = gossip_sent.astype(i32).sum(1, keepdims=True) \
            + joinreq_sent.astype(i32) \
            + jnp.where(is_intro, rep_total, 0)
        recv_row = recv_from.astype(i32).sum(1, keepdims=True) \
            + jrep.astype(i32) \
            + jnp.where(is_intro, req_total, 0)
        sent_o[pl.ds(s, 1), :] = jnp.transpose(sent_row)
        recv_o[pl.ds(s, 1), :] = jnp.transpose(recv_row)

        # ---- write the end-of-tick state ---------------------------
        known_o[:] = known.astype(i32)
        hb_o[:] = hb
        ts_o[:] = ts
        gossip_o[:] = gossip_next.astype(i32)
        aux_o[:] = jnp.concatenate(
            [in_group.astype(i32), own_hb,
             joinreq_next.astype(i32), joinrep_next.astype(i32),
             aux[:, _START:]], axis=1)
        return ()

    jax.lax.fori_loop(0, s_ticks, tick, (), unroll=False)


@functools.partial(jax.jit,
                   static_argnames=("n", "s_ticks", "t_remove",
                                    "can_rejoin", "with_events",
                                    "interpret"))
def dense_mega_ticks(known, hb, ts, gossip, aux, gdrop, qdrop, pdrop,
                     sp, *, n: int, s_ticks: int, t_remove: int,
                     can_rejoin: bool, with_events: bool = False,
                     interpret: bool | None = None):
    """Run ``s_ticks`` whole dense ticks in one Pallas launch.

    Args:
      known/hb/ts/gossip: i32[N, N] state planes (bools as 0/1).
      aux: i32[N, 8] — [in_group, own_hb, joinreq, joinrep, start,
        fail, rejoin, pad] (see lane constants).
      gdrop: bool[S, N, N]; qdrop/pdrop: bool[S, N] — the launch's
        drop decisions, precomputed with ops/drop.py's exact streams.
      sp: i32[1] — [t0].

    Returns ``(known', hb', ts', gossip', aux', sent i32[S, N],
    recv i32[S, N])``, plus ``(added i8[S, N, N], removed i8[S, N, N])``
    when ``with_events``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    assert known.shape == (n, n) and n % 8 == 0
    i32 = jnp.int32
    n_out = 9 if with_events else 7
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 8,
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_out,
        scratch_shapes=[pltpu.VMEM((n, n), i32),
                        pltpu.VMEM((n, n), i32),
                        pltpu.VMEM((8, n), i32)],
    )
    ev_shapes = [jax.ShapeDtypeStruct((s_ticks, n, n), jnp.int8)] * 2 \
        if with_events else []
    out = pl.pallas_call(
        functools.partial(_kernel, n, s_ticks, t_remove, can_rejoin,
                          with_events),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((n, n), i32),
                   jax.ShapeDtypeStruct((n, n), i32),
                   jax.ShapeDtypeStruct((n, n), i32),
                   jax.ShapeDtypeStruct((n, n), i32),
                   jax.ShapeDtypeStruct((n, DENSE_AUX_LANES), i32),
                   jax.ShapeDtypeStruct((s_ticks, n), i32),
                   jax.ShapeDtypeStruct((s_ticks, n), i32)]
        + ev_shapes,
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=110 * 1024 * 1024),
        interpret=interpret,
    )(sp, known, hb, ts, gossip, aux, gdrop, qdrop, pdrop)
    return out
