"""Pallas TPU kernels + version-compat shims.

JAX renamed the Pallas TPU compiler-params dataclass across releases:
older releases (including the 0.4.x line installed here) spell it
``pltpu.TPUCompilerParams``; newer ones spell it
``pltpu.CompilerParams``.  Every kernel in this package goes through
:func:`tpu_compiler_params` so both spellings work unmodified.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

#: the installed JAX's Pallas TPU compiler-params class (new spelling
#: preferred, old spelling accepted)
TPUCompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` on any supported JAX.

    All call sites pass keyword arguments only, and the fields used
    here (``vmem_limit_bytes``, ``dimension_semantics``) exist under
    both spellings.
    """
    return TPUCompilerParams(**kwargs)
