"""Fused Pallas TPU kernel: the dense tick's post-merge epilogue.

Through this TPU stack each XLA kernel launch inside the scan costs
~10µs regardless of size, and the dense tick's post-merge phase is a
chain of ~30 small (N, N) elementwise ops — more than half the tick's
wall time at N=512.  This kernel computes, per (row, col) cell and in
one VMEM-resident pass:

  1. the merge-into-existing / piggyback-add / direct-sender /
     JOINREQ / JOINREP membership updates (core/tick.py's
     checkMessages phase) from the three merge maxima;
  2. staleness detection (nodeLoopOps, MP1Node.cpp:339-348);
  3. dissemination + drop masking + the in-flight hold
     (EmulNet ENsend semantics), producing the next gossip matrix;
  4. per-row sent counters and (in trace mode) the add/remove event
     masks.

The merge maxima themselves arrive as inputs: they are computed by the
MXU level decomposition (ops/merge.py gossip_reductions_mxu), which
replaced both this kernel's former in-kernel VPU accumulation loop and
the standalone maxmerge Pallas kernel — one boolean matmul per
distinct column value beats O(N³) VPU product-max by the measured
end-to-end factor of ~2x at N=512.

Grid is (R/TR,): row tiles spanning the full peer axis so the JOINREP
column (col 0) and row sums stay tile-local.

The kernel is differentially tested against the unfused XLA tick for
bit-identical states, events, and accounting (tests/test_tickfused.py)
and is used by the LocalComm path only (the sharded ring path keeps
the composable ops).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tpu_compiler_params


def _kernel(t_remove: int, tr: int, n: int, with_events: bool,
            # inputs
            scalars_ref,                     # SMEM: [t]
            ma_ref, mf_ref, mt_ref,          # (TR, N)  merge maxima (public,
                                             #   FILL=-1 encodes "none")
            dfull_ref,                       # (TR, N)  recv_from row tile
            kn_ref, hb_ref, ts_ref,          # (TR, N)  receiver row tiles
            gossip_ref, gdrop_ref,           # (TR, N)
            rowvec_ref,                      # (TR, 4)  [ops, jrep, -, -]
            colvec_ref,                      # (4, N)   [jreq, live_hold, -, -]
            # outputs (added/removed only in trace mode)
            *outs):
    if with_events:
        (kn_out, hb_out, ts_out, gossip_out, counters_out,
         added_out, removed_out) = outs
    else:
        (kn_out, hb_out, ts_out, gossip_out, counters_out) = outs
        added_out = removed_out = None
    i_tile = pl.program_id(0)
    t = scalars_ref[0]

    m_all = ma_ref[:]
    m_fr = mf_ref[:]
    t_fr = mt_ref[:]
    anyf = t_fr >= 0

    grow = i_tile * tr + jax.lax.broadcasted_iota(jnp.int32, (tr, n), 0)
    gcol = jax.lax.broadcasted_iota(jnp.int32, (tr, n), 1)
    self_mask = grow == gcol
    is_row0 = grow == 0
    is_col0 = gcol == 0

    exists = kn_ref[:] > 0
    hb0 = hb_ref[:]
    ts0 = ts_ref[:]
    dfull = dfull_ref[:] > 0
    ops_r = rowvec_ref[:, 0:1] > 0                        # (TR, 1)
    jrep_r = rowvec_ref[:, 1:2] > 0
    jreq_c = jnp.expand_dims(colvec_ref[0, :], 0) > 0     # (1, N)
    hold_c = jnp.expand_dims(colvec_ref[1, :], 0) > 0

    # merge into existing entries (MP1Node.cpp:248-251)
    inc = exists & (m_all > hb0)
    hb1 = jnp.where(inc, m_all, hb0)
    ts1 = jnp.where(inc, t, ts0)
    # piggyback add (MP1Node.cpp:282-301)
    padd = (~exists) & anyf & (~self_mask)
    hb1 = jnp.where(padd, m_all, hb1)
    ts1 = jnp.where(padd, jnp.where(m_all > m_fr, t, t_fr), ts1)
    known_pb = exists | padd
    # direct-sender handling (MP1Node.cpp:236-242)
    dinc = dfull & known_pb
    hb1 = jnp.where(dinc, hb1 + 1, hb1)
    ts1 = jnp.where(dinc, t, ts1)
    dadd = dfull & (~known_pb) & (~self_mask)
    hb1 = jnp.where(dadd, 1, hb1)
    ts1 = jnp.where(dadd, t, ts1)
    known2 = exists | padd | dadd
    # JOINREQ at the introducer (row 0; MP1Node.cpp:221-230)
    q_cell = is_row0 & jreq_c & (~known2) & (~is_col0)
    known3 = known2 | q_cell
    hb1 = jnp.where(q_cell, 1, hb1)
    ts1 = jnp.where(q_cell, t, ts1)
    # JOINREP at the joiner (col 0; MP1Node.cpp:231-233)
    r_cell = is_col0 & jrep_r & (~known3)
    known4 = known3 | r_cell
    hb1 = jnp.where(r_cell, 1, hb1)
    ts1 = jnp.where(r_cell, t, ts1)
    # staleness detection (MP1Node.cpp:339-348)
    stale = ops_r & known4 & (t - ts1 >= t_remove)
    known5 = known4 & (~stale)
    # dissemination + drop + in-flight hold
    send = ops_r & known5
    gsent = send & (gdrop_ref[:] == 0)
    gossip_next = gsent | ((gossip_ref[:] > 0) & hold_c)

    kn_out[:] = known5.astype(jnp.int32)
    hb_out[:] = hb1
    ts_out[:] = ts1
    gossip_out[:] = gossip_next.astype(jnp.int32)
    sent_row = gsent.astype(jnp.int32).sum(1)
    counters_out[:] = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (tr, 128), 1) == 0,
        jnp.expand_dims(sent_row, 1), 0)
    if with_events:
        added_out[:] = (known4 & (~exists)).astype(jnp.int32)
        removed_out[:] = stale.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("t_remove", "tile_r",
                                             "with_events", "interpret"))
def fused_tick_update(m_all, m_fresh, t_fresh, recv_from,
                      known, hb, ts, gossip, gdrop,
                      ops, jrep, jreq, live_hold, t, *,
                      t_remove: int, tile_r: int = 64,
                      with_events: bool = True,
                      interpret: bool | None = None):
    """One fused pass over the post-merge tick update.

    ``m_all/m_fresh/t_fresh`` are the public merge maxima
    (gossip_reductions / gossip_reductions_mxu contract, FILL=-1);
    the other args mirror core/tick.py's intermediates: ``recv_from``
    [R, S] delivery, ``known/hb/ts`` the post-wipe state tables,
    ``gossip`` the in-flight matrix, ``gdrop`` this tick's gossip drop
    mask, ``ops``/``jrep`` per-row vectors, ``jreq``/``live_hold``
    per-column vectors, ``t`` the clock.

    Returns (known', hb', ts', gossip', sent_row[N], added, removed);
    ``added``/``removed`` are None when ``with_events`` is False.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = known.shape[0]
    tr = min(tile_r, n)
    assert n % tr == 0 and tr % 8 == 0, (n, tr)

    i32 = jnp.int32
    rowvec = jnp.stack([ops.astype(i32), jrep.astype(i32),
                        jnp.zeros(n, i32), jnp.zeros(n, i32)], axis=1)
    colvec = jnp.stack([jreq.astype(i32), live_hold.astype(i32),
                        jnp.zeros(n, i32), jnp.zeros(n, i32)])

    grid = (n // tr,)
    row_tile = pl.BlockSpec((tr, n), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    out_specs = [row_tile, row_tile, row_tile, row_tile,
                 pl.BlockSpec((tr, 128), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((n, n), i32)] * 4 \
        + [jax.ShapeDtypeStruct((n, 128), i32)]
    if with_events:
        out_specs += [row_tile, row_tile]
        out_shape += [jax.ShapeDtypeStruct((n, n), i32)] * 2

    outs = pl.pallas_call(
        functools.partial(_kernel, t_remove, tr, n, with_events),
        grid=grid,
        # ~17 double-buffered (TR, N) planes exceed the default 16 MB
        # scoped window at N=4096 (the old n<=2048 envelope); v5e has
        # 128 MB of physical VMEM
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=96 * 1024 * 1024),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                # scalars
            row_tile, row_tile, row_tile,                         # maxima
            row_tile,                                             # dfull
            row_tile, row_tile, row_tile,                         # kn/hb/ts
            row_tile, row_tile,                                   # gossip gdrop
            pl.BlockSpec((tr, 4), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),                # rowvec
            pl.BlockSpec((4, n), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),                # colvec
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(jnp.asarray([t], i32),
      m_all.astype(i32), m_fresh.astype(i32), t_fresh.astype(i32),
      recv_from.astype(i32),
      known.astype(i32), hb.astype(i32), ts.astype(i32),
      gossip.astype(i32), gdrop.astype(i32),
      rowvec, colvec)

    kn2, hb2, ts2, gossip2, counters = outs[:5]
    sent_row = counters[:, 0]
    if not with_events:
        return kn2 > 0, hb2, ts2, gossip2 > 0, sent_row, None, None
    added, removed = outs[5], outs[6]
    return kn2 > 0, hb2, ts2, gossip2 > 0, sent_row, added > 0, removed > 0
