"""Fused Pallas TPU kernel: gossip merge + the whole per-cell tick
update in one pass.

Through this TPU stack each XLA kernel launch inside the scan costs
~10µs regardless of size, and the dense tick's post-merge phase is a
chain of ~30 small (N, N) elementwise ops — more than half the tick's
wall time at N=512.  This kernel computes, per (row, col) cell and in
one VMEM-resident pass:

  1. the three product-max merge reductions over the sender axis
     (identical contract to ops/merge.py — the (max, and) semiring
     replacement for MP1Node.cpp:236-256);
  2. the merge-into-existing / piggyback-add / direct-sender /
     JOINREQ / JOINREP membership updates (core/tick.py's
     checkMessages phase);
  3. staleness detection (nodeLoopOps, MP1Node.cpp:339-348);
  4. dissemination + drop masking + the in-flight hold
     (EmulNet ENsend semantics), producing the next gossip matrix;
  5. per-row sent counters and (in trace mode) the add/remove event
     masks.

Grid is (R/TR, 1, S/TS): the sender axis is innermost and accumulates
the merge maxima in VMEM scratch; the epilogue (2-5) runs once at the
last sender step.  Column tiles span the full peer axis so the
JOINREP column (col 0) and row sums stay tile-local.

The kernel is differentially tested against the unfused XLA tick for
bit-identical states, events, and accounting (tests/test_tickfused.py)
and is used by the LocalComm path only (the sharded ring path keeps
the composable ops).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SUB = 8  # sender sublane chunk of the merge loop


def _kernel(t_remove: int, tr: int, ts_tile: int, n: int, with_events: bool,
            num_k: int,
            # inputs
            scalars_ref,                     # SMEM: [t]
            d_ref,                           # (TR, TS)   recv_from k-tile
            kn_s_ref, hb_s_ref, ts_s_ref,    # (TS, N)    sender payload tiles
            dfull_ref,                       # (TR, N)    recv_from row tile
            kn_ref, hb_ref, ts_ref,          # (TR, N)    receiver row tiles
            gossip_ref, gdrop_ref,           # (TR, N)
            rowvec_ref,                      # (TR, 4)    [ops, jrep, -, -]
            colvec_ref,                      # (4, N)     [jreq, live_hold, -, -]
            # outputs (added/removed only in trace mode), then scratch
            *refs):
    if with_events:
        (kn_out, hb_out, ts_out, gossip_out, counters_out,
         added_out, removed_out, m_a, m_f, m_t) = refs
    else:
        (kn_out, hb_out, ts_out, gossip_out, counters_out,
         m_a, m_f, m_t) = refs
        added_out = removed_out = None
    k = pl.program_id(2)
    # read outside the pl.when closures: the interpret-mode lowering
    # resolves program_id only in the top-level kernel jaxpr
    i_tile = pl.program_id(0)

    @pl.when(k == 0)
    def _init():
        m_a[:] = jnp.zeros_like(m_a)
        m_f[:] = jnp.zeros_like(m_f)
        m_t[:] = jnp.zeros_like(m_t)

    t = scalars_ref[0]

    # ---- merge accumulation over this sender tile ------------------
    kn_s = kn_s_ref[:]
    hb_s = hb_s_ref[:]
    ts_s = ts_s_ref[:]
    a1 = kn_s * (hb_s + 1)
    fresh = kn_s * (t - ts_s < t_remove)
    f1 = fresh * (hb_s + 1)
    t1 = fresh * (ts_s + 1)
    d = d_ref[:]
    a1x = jnp.expand_dims(a1, 0)
    f1x = jnp.expand_dims(f1, 0)
    t1x = jnp.expand_dims(t1, 0)
    for r0 in range(0, tr, _SUB):
        dx = jnp.expand_dims(d[r0:r0 + _SUB, :], 2)      # (8, TS, 1)
        m_a[r0:r0 + _SUB, :] = jnp.maximum(
            m_a[r0:r0 + _SUB, :], (dx * a1x).max(1))
        m_f[r0:r0 + _SUB, :] = jnp.maximum(
            m_f[r0:r0 + _SUB, :], (dx * f1x).max(1))
        m_t[r0:r0 + _SUB, :] = jnp.maximum(
            m_t[r0:r0 + _SUB, :], (dx * t1x).max(1))

    # ---- epilogue: the whole tick update, once --------------------
    @pl.when(k == num_k - 1)
    def _epilogue():
        m_all = m_a[:] - 1
        m_fr = m_f[:] - 1
        t_fr = m_t[:] - 1
        anyf = m_t[:] > 0

        grow = i_tile * tr + jax.lax.broadcasted_iota(
            jnp.int32, (tr, n), 0)
        gcol = jax.lax.broadcasted_iota(jnp.int32, (tr, n), 1)
        self_mask = grow == gcol
        is_row0 = grow == 0
        is_col0 = gcol == 0

        exists = kn_ref[:] > 0
        hb0 = hb_ref[:]
        ts0 = ts_ref[:]
        dfull = dfull_ref[:] > 0
        ops_r = rowvec_ref[:, 0:1] > 0                        # (TR, 1)
        jrep_r = rowvec_ref[:, 1:2] > 0
        jreq_c = jnp.expand_dims(colvec_ref[0, :], 0) > 0     # (1, N)
        hold_c = jnp.expand_dims(colvec_ref[1, :], 0) > 0

        # merge into existing entries (MP1Node.cpp:248-251)
        inc = exists & (m_all > hb0)
        hb1 = jnp.where(inc, m_all, hb0)
        ts1 = jnp.where(inc, t, ts0)
        # piggyback add (MP1Node.cpp:282-301)
        padd = (~exists) & anyf & (~self_mask)
        hb1 = jnp.where(padd, m_all, hb1)
        ts1 = jnp.where(padd, jnp.where(m_all > m_fr, t, t_fr), ts1)
        known_pb = exists | padd
        # direct-sender handling (MP1Node.cpp:236-242)
        dinc = dfull & known_pb
        hb1 = jnp.where(dinc, hb1 + 1, hb1)
        ts1 = jnp.where(dinc, t, ts1)
        dadd = dfull & (~known_pb) & (~self_mask)
        hb1 = jnp.where(dadd, 1, hb1)
        ts1 = jnp.where(dadd, t, ts1)
        known2 = exists | padd | dadd
        # JOINREQ at the introducer (row 0; MP1Node.cpp:221-230)
        q_cell = is_row0 & jreq_c & (~known2) & (~is_col0)
        known3 = known2 | q_cell
        hb1 = jnp.where(q_cell, 1, hb1)
        ts1 = jnp.where(q_cell, t, ts1)
        # JOINREP at the joiner (col 0; MP1Node.cpp:231-233)
        r_cell = is_col0 & jrep_r & (~known3)
        known4 = known3 | r_cell
        hb1 = jnp.where(r_cell, 1, hb1)
        ts1 = jnp.where(r_cell, t, ts1)
        # staleness detection (MP1Node.cpp:339-348)
        stale = ops_r & known4 & (t - ts1 >= t_remove)
        known5 = known4 & (~stale)
        # dissemination + drop + in-flight hold
        send = ops_r & known5
        gsent = send & (gdrop_ref[:] == 0)
        gossip_next = gsent | ((gossip_ref[:] > 0) & hold_c)

        kn_out[:] = known5.astype(jnp.int32)
        hb_out[:] = hb1
        ts_out[:] = ts1
        gossip_out[:] = gossip_next.astype(jnp.int32)
        sent_row = gsent.astype(jnp.int32).sum(1)
        counters_out[:] = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (tr, 128), 1) == 0,
            jnp.expand_dims(sent_row, 1), 0)
        if with_events:
            added_out[:] = (known4 & (~exists)).astype(jnp.int32)
            removed_out[:] = stale.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("t_remove", "tile_r", "tile_s",
                                             "with_events", "interpret"))
def fused_tick_update(recv_from, known, hb, ts, gossip, gdrop,
                      ops, jrep, jreq, live_hold, t, *,
                      t_remove: int, tile_r: int = 64, tile_s: int = 128,
                      with_events: bool = True,
                      interpret: bool | None = None):
    """One fused pass: merge + membership update + detection + send.

    Args mirror core/tick.py's intermediates: ``recv_from`` [R, S]
    delivery, ``known/hb/ts`` the post-wipe state tables, ``gossip``
    the in-flight matrix, ``gdrop`` this tick's gossip drop mask,
    ``ops``/``jrep`` per-row vectors, ``jreq``/``live_hold`` per-column
    vectors, ``t`` the clock.

    Returns (known', hb', ts', gossip', sent_row[N], added, removed);
    ``added``/``removed`` are None when ``with_events`` is False.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = known.shape[0]
    tr = min(tile_r, n)
    tss = min(tile_s, n)
    assert n % tr == 0 and n % tss == 0 and tss % _SUB == 0 \
        and tr % _SUB == 0, (n, tr, tss)

    i32 = jnp.int32
    rowvec = jnp.stack([ops.astype(i32), jrep.astype(i32),
                        jnp.zeros(n, i32), jnp.zeros(n, i32)], axis=1)
    colvec = jnp.stack([jreq.astype(i32), live_hold.astype(i32),
                        jnp.zeros(n, i32), jnp.zeros(n, i32)])

    grid = (n // tr, 1, n // tss)
    row_tile = pl.BlockSpec((tr, n), lambda i, j, k: (i, 0),
                            memory_space=pltpu.VMEM)
    out_specs = [row_tile, row_tile, row_tile, row_tile,
                 pl.BlockSpec((tr, 128), lambda i, j, k: (i, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((n, n), i32)] * 4 \
        + [jax.ShapeDtypeStruct((n, 128), i32)]
    if with_events:
        out_specs += [row_tile, row_tile]
        out_shape += [jax.ShapeDtypeStruct((n, n), i32)] * 2

    outs = pl.pallas_call(
        functools.partial(_kernel, t_remove, tr, tss, n, with_events,
                          n // tss),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                # scalars
            pl.BlockSpec((tr, tss), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),                # d k-tile
            pl.BlockSpec((tss, n), lambda i, j, k: (k, 0),
                         memory_space=pltpu.VMEM),                # kn sender
            pl.BlockSpec((tss, n), lambda i, j, k: (k, 0),
                         memory_space=pltpu.VMEM),                # hb sender
            pl.BlockSpec((tss, n), lambda i, j, k: (k, 0),
                         memory_space=pltpu.VMEM),                # ts sender
            row_tile,                                             # dfull
            row_tile, row_tile, row_tile,                         # kn/hb/ts row
            row_tile, row_tile,                                   # gossip gdrop
            pl.BlockSpec((tr, 4), lambda i, j, k: (i, 0),
                         memory_space=pltpu.VMEM),                # rowvec
            pl.BlockSpec((4, n), lambda i, j, k: (0, 0),
                         memory_space=pltpu.VMEM),                # colvec
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((tr, n), i32)] * 3,
        interpret=interpret,
    )(jnp.asarray([t], i32),
      recv_from.astype(i32),
      known.astype(i32), hb.astype(i32), ts.astype(i32),
      recv_from.astype(i32),
      known.astype(i32), hb.astype(i32), ts.astype(i32),
      gossip.astype(i32), gdrop.astype(i32),
      rowvec, colvec)

    kn2, hb2, ts2, gossip2, counters = outs[:5]
    sent_row = counters[:, 0]
    if not with_events:
        return kn2 > 0, hb2, ts2, gossip2 > 0, sent_row, None, None
    added, removed = outs[5], outs[6]
    return kn2 > 0, hb2, ts2, gossip2 > 0, sent_row, added > 0, removed > 0
