"""Fused Pallas TPU kernel: overlay XOR exchange + hash-slot merge.

The overlay tick's hot phase (models/overlay.py) is, per exchange round
``f``: permute the whole payload matrix by ``x[i ^ m_f]`` and fold the
permuted candidate entries into the per-receiver hash-slotted view
tables.  The XLA formulation pays for both halves:

* the XOR permutation is two HIGHEST-precision f32 permutation matmuls
  of O(sqrt(N)) contraction depth — O(N^1.5 * C) FLOPs that dominate
  the tick at the 1M-peer BASELINE config;
* the merge materializes (N, K, L+1) broadcast intermediates in HBM,
  several GB of transient traffic per tick at 65k.

This kernel does both in one launch with the permutation *free* and
the merge VMEM-resident:

* the shard-free high bits of ``i ^ m`` are folded into the grid's
  **block index map** (block ``i`` DMAs source block ``i ^ (m >> lgB)``
  — the mask is a scalar-prefetch argument, so the DMA address is
  known before the body runs);
* the low bits are a **butterfly network in VMEM**: for each set bit
  ``j`` of ``m % B``, rows swap with their ``r ^ 2^j`` partner — a
  static rotate + select per bit, exact integer moves (the f32
  matmul's bf16-truncation hazard is gone by construction);
* the hash-slot merge is a serial pass over the L+1 candidate columns,
  each a lexicographic (key, payload) max into the (B, K) accumulators
  held in the output refs, which stay VMEM-resident across the F grid
  steps (the output block index ignores the round axis).

Per tick the kernel reads the payload F times and the accumulators
once — ~250 MB of HBM traffic at N=65536 versus the multi-GB XLA
path, and no matmuls at all.

Semantics are bit-identical to the XLA merge chain in
models/overlay.py (same `_pack_key`/`_pack_th` contract, same
candidate validity; lexicographic max is order-free, so fusing the
rounds cannot change the winner).  Differentially tested in
tests/test_overlay_pallas.py; the receiver-side ``proc`` gate and the
JOINREQ/JOINREP merges stay outside (models/overlay.py applies them —
the merge is commutative, so ordering is free).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _roll_rows(x, shift: int):
    """Circular roll along axis 0 by a static shift (concat of static
    slices — lowers unconditionally in Mosaic and interpret mode)."""
    s = shift % x.shape[0]
    if s == 0:
        return x
    return jnp.concatenate([x[-s:], x[:-s]], axis=0)


def _kernel(b: int, c: int, k: int, l: int, f_rounds: int, t_remove: int,
            # scalar prefetch: [t, seed, m_0 .. m_{F-1}]
            sp_ref,
            # inputs
            payload_ref,                  # (B, C) block, pre-XOR'd high bits
            curkey_ref, curp_ref,         # (B, K) accumulator init
            # outputs (accumulated across the round axis)
            kmax_ref, pacc_ref, recv_ref):
    from ...models.overlay import _pack_key, _pack_th
    from ...utils.hash32 import mix32

    fi = pl.program_id(1)
    i_blk = pl.program_id(0)

    @pl.when(fi == 0)
    def _init():
        kmax_ref[:] = curkey_ref[:]
        pacc_ref[:] = curp_ref[:]
        recv_ref[:] = jnp.zeros_like(recv_ref)

    t = sp_ref[0]
    seed = sp_ref[1].astype(jnp.uint32)
    m = sp_ref[2 + fi]

    # ---- butterfly: finish the XOR permutation's low bits ----------
    w = payload_ref[:]
    rbits = jax.lax.broadcasted_iota(jnp.int32, (b, 1), 0)
    lgb = b.bit_length() - 1
    for j in range(lgb):
        s = 1 << j
        swapped = jnp.where(((rbits >> j) & 1) == 0,
                            _roll_rows(w, -s), _roll_rows(w, s))
        w = jnp.where(((m >> j) & 1) == 1, swapped, w)

    # ---- candidate merge: lexicographic (key, packed ts/hb) max ----
    rows = i_blk * b + rbits                       # (B, 1) global rows
    rows_u = rows.astype(jnp.uint32)
    partner = rows ^ m
    # this round's send flag: fi is traced, so select the column with
    # an iota compare instead of a dynamic lane slice
    flags_all = w[:, 3 * l + 1:3 * l + 1 + f_rounds]            # (B, F)
    fsel = jax.lax.broadcasted_iota(jnp.int32, (b, f_rounds), 1) == fi
    flag = jnp.where(fsel, flags_all, 0).max(axis=1, keepdims=True) > 0
    kk = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)

    kmax = kmax_ref[:]
    pacc = pacc_ref[:]
    for cand in range(l + 1):
        if cand < l:
            c_id = w[:, cand:cand + 1]
            c_hb = w[:, l + cand:l + cand + 1]
            c_ts = w[:, 2 * l + cand:2 * l + cand + 1]
            fresh = t - c_ts < t_remove
        else:                              # the partner's self-entry
            c_id = partner
            c_hb = w[:, 3 * l:3 * l + 1]
            c_ts = jnp.full_like(c_id, 0) + (t - 1)
            # its age is exactly 1, so freshness is static in t_remove
            fresh = t_remove > 1
        valid = flag & (c_id >= 0) & fresh & (c_id != rows)
        c_idu = c_id.astype(jnp.uint32)
        slot = (mix32(seed, rows_u, c_idu) % k).astype(jnp.int32)
        keyc = jnp.where(valid, _pack_key(seed, t, rows_u, c_id, c_ts),
                         jnp.uint32(0))
        pc = jnp.where(valid, _pack_th(c_ts, c_hb), 0)
        match = slot == kk                           # (B, K)
        ck = jnp.where(match, keyc, jnp.uint32(0))
        cp = jnp.where(match, pc, 0)
        better = (ck > kmax) | ((ck == kmax) & (cp > pacc))
        kmax = jnp.where(better, ck, kmax)
        pacc = jnp.where(better, cp, pacc)
    kmax_ref[:] = kmax
    pacc_ref[:] = pacc

    lane0 = jax.lax.broadcasted_iota(jnp.int32, (b, 128), 1) == 0
    recv_ref[:] = recv_ref[:] + jnp.where(lane0, flag.astype(jnp.int32), 0)


@functools.partial(jax.jit,
                   static_argnames=("k", "l", "t_remove", "block_rows",
                                    "interpret"))
def fused_exchange_merge(payload, cur_key, cur_p, masks, t, seed, *,
                         k: int, l: int, t_remove: int,
                         block_rows: int = 256,
                         interpret: bool | None = None):
    """All F exchange rounds' permute+merge in one Pallas launch.

    Args:
      payload: i32[N, 3L+1+F] — per sender row: L-window ids, hbs, tss,
        own_hb, then the F per-round send flags (0/1).
      cur_key/cur_p: u32/i32[N, K] — accumulators' initial value (the
        receiver's current table keys, models/overlay.py).
      masks: i32[F] — this tick's XOR masks ``m_f`` (all in [1, N)).
      t, seed: the clock (i32) and hash seed (u32).

    Returns ``(keymax u32[N, K], p_acc i32[N, K], recv i32[N])`` with
    NO receiver-side ``proc`` gating — the caller selects
    ``where(proc, result, initial)`` (bit-equal because an invalid
    receiver's accumulator is simply discarded).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, c = payload.shape
    f_rounds = int(masks.shape[0])
    assert c == 3 * l + 1 + f_rounds, (c, l, f_rounds)
    b = min(block_rows, n)
    assert n % b == 0 and b & (b - 1) == 0 and b >= 8, (n, b)
    nb = n // b

    i32 = jnp.int32
    sp = jnp.concatenate([
        jnp.asarray([t], i32).reshape(1),
        seed.astype(i32).reshape(1),
        masks.astype(i32).reshape(f_rounds)])

    row_block = lambda i, fi, sp_ref: (i, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, f_rounds),
        in_specs=[
            pl.BlockSpec((b, c),
                         lambda i, fi, sp_ref: (i ^ (sp_ref[2 + fi] // b), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((b, 128), row_block, memory_space=pltpu.VMEM),
        ],
    )
    kmax, pacc, recv = pl.pallas_call(
        functools.partial(_kernel, b, c, k, l, f_rounds, t_remove),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n, k), jnp.uint32),
            jax.ShapeDtypeStruct((n, k), i32),
            jax.ShapeDtypeStruct((n, 128), i32),
        ],
        interpret=interpret,
    )(sp, payload, cur_key, cur_p)
    return kmax, pacc, recv[:, 0]
