"""Fused Pallas TPU kernel: overlay XOR exchange + lane-aligned merge.

The overlay tick's hot phase (models/overlay.py) is, per exchange round
``f``: permute the whole payload matrix by ``x[i ^ m_f]`` and fold the
incoming view into the receiver's table.  The XLA formulation pays two
HIGHEST-precision f32 permutation matmuls of O(sqrt(N)) contraction
depth per round — O(N^1.5 · K) FLOPs that dominate the tick at the
1M-peer BASELINE config.  This kernel makes the permutation nearly
free and keeps every round VMEM-resident:

* grid = row blocks only; each step DMAs all F source blocks (the same
  payload array bound F times, each with its own scalar-prefetched
  **block index map** ``i ^ (m_f >> lgB)`` routing the mask's high
  bits) and merges all F rounds into the accumulators in registers;
* the mask's low bits are a **butterfly network in VMEM**: for each
  set bit ``j`` of ``m % B``, rows swap with their ``r ^ 2^j`` partner
  — a static rotate + select, predicated with ``pl.when`` so unset
  bits cost nothing, exact integer moves (no bf16-truncation hazard);
* entries travel packed — id word + ``_pack_th``-packed (ts, hb) word,
  2K+1+F lanes per row — so the butterfly moves half the data of a
  separate-planes layout, and the packed word IS the merge tiebreak
  payload;
* because tables are slotted by the global epoch map (models/overlay.py
  design), the merge itself is a **lane-aligned lexicographic
  (key, payload) max** on (B, K) — no slot-match product — plus a
  one-hot merge of the partner's self-entry.

Per tick the kernel reads the payload F times and the accumulators
once; there are no matmuls at all.

Semantics are bit-identical to the XLA phases in models/overlay.py
(same ``_pack_key``/``_pack_th``/``_slot_of`` contract, same candidate
validity; lexicographic max is order-free, so fusing the rounds cannot
change the winner).  Differentially tested in
tests/test_overlay_pallas.py; the receiver-side ``proc`` gate and the
JOINREQ/JOINREP merges stay outside (models/overlay.py applies them —
the merge is commutative, so ordering is free).

Mosaic workarounds (observed on v5e): ``_pack_key`` must use the
masked single-shift tie form — the ``(h >> 24) << 21`` shift pair
miscompiles in large kernel contexts (small tie values land as 0); and
``jnp.maximum`` on uint32 vectors does not legalize (``arith.maxui``),
so the lexicographic merge sticks to compare+select.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _roll_rows(x, shift: int):
    """Circular roll along axis 0 by a static shift (concat of static
    slices — lowers unconditionally in Mosaic and interpret mode)."""
    s = shift % x.shape[0]
    if s == 0:
        return x
    return jnp.concatenate([x[-s:], x[:-s]], axis=0)


def _kernel(b: int, c: int, k: int, f_rounds: int, t_remove: int,
            # scalar prefetch: [t, seed, m_0 .. m_{F-1}]
            sp_ref,
            # inputs: the payload bound once per round + accumulator init
            *refs):
    from ...models.overlay import (SLOT_EPOCH, _pack_key, _pack_key_direct,
                                   _pack_th, _slot_of)

    prefs = refs[:f_rounds]
    curkey_ref, curp_ref, kmax_ref, pacc_ref, w_ref = refs[f_rounds:]

    i_blk = pl.program_id(0)
    t = sp_ref[0]
    seed = sp_ref[1].astype(jnp.uint32)

    rbits = jax.lax.broadcasted_iota(jnp.int32, (b, 1), 0)
    rows = i_blk * b + rbits                       # (B, 1) global rows
    rows_u = rows.astype(jnp.uint32)
    kk = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)
    lgb = b.bit_length() - 1
    slot_ep = (t // SLOT_EPOCH).astype(jnp.uint32)

    kmax = curkey_ref[:]
    pacc = curp_ref[:]
    recv = jnp.zeros((b, 1), jnp.int32)
    for fi in range(f_rounds):
        m = sp_ref[2 + fi]
        # ---- butterfly: the XOR permutation's low bits, predicated
        # per mask bit (unset bits cost nothing) ---------------------
        w_ref[:] = prefs[fi][:]
        for j in range(lgb):
            s = 1 << j

            @pl.when(((m >> j) & 1) == 1)
            def _swap(s=s, j=j):
                cur = w_ref[:]
                w_ref[:] = jnp.where(((rbits >> j) & 1) == 0,
                                     _roll_rows(cur, -s), _roll_rows(cur, s))
        w = w_ref[:]

        # ---- lane-aligned view merge ------------------------------
        flag = w[:, 2 * k + 1 + fi:2 * k + 2 + fi] > 0   # (B, 1)
        in_ids = w[:, :k]
        in_p = w[:, k:2 * k]
        in_ts = (in_p >> 12) - 1
        valid = flag & (in_ids >= 0) & (t - in_ts < t_remove) \
            & (in_ids != rows)
        key = jnp.where(valid, _pack_key(seed, t, rows_u, in_ids, in_ts),
                        jnp.uint32(0))
        p = jnp.where(valid, in_p, 0)
        better = (key > kmax) | ((key == kmax) & (p > pacc))
        kmax = jnp.where(better, key, kmax)
        pacc = jnp.where(better, p, pacc)

        # ---- the partner's self-entry (one-hot; age exactly 1) ----
        if t_remove > 1:
            partner = rows ^ m
            psl = _slot_of(seed, slot_ep, partner, k)           # (B, 1)
            e_ts = jnp.zeros_like(partner) + (t - 1)
            pkey = jnp.where(flag, _pack_key_direct(t, partner, e_ts),
                             jnp.uint32(0))
            pp = jnp.where(flag, _pack_th(e_ts, w[:, 2 * k:2 * k + 1]), 0)
            match = psl == kk
            ck = jnp.where(match, pkey, jnp.uint32(0))
            cp = jnp.where(match, pp, 0)
            better = (ck > kmax) | ((ck == kmax) & (cp > pacc))
            kmax = jnp.where(better, ck, kmax)
            pacc = jnp.where(better, cp, pacc)

        recv = recv + flag.astype(jnp.int32)

    kmax_ref[:] = kmax
    # the pacc output is (B, 2K) — lanes [0, K) carry the payload
    # accumulator and lane K the per-row recv count.  A (N, K) i32
    # array is lane-padded to 128 in TPU tiling anyway, so the widened
    # output costs no extra HBM and saves a separate (N, 128) buffer.
    lane0 = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1) == 0
    pacc_ref[:] = jnp.concatenate([pacc, jnp.where(lane0, recv, 0)], axis=1)


@functools.partial(jax.jit,
                   static_argnames=("k", "t_remove", "block_rows",
                                    "interpret"))
def fused_exchange_merge(payload, cur_key, cur_p, masks, t, seed, *,
                         k: int, t_remove: int,
                         block_rows: int = 512,
                         interpret: bool | None = None):
    """All F exchange rounds' permute+merge in one Pallas launch.

    Args:
      payload: i32[N, 2K+1+F] — per sender row: the K-slot view's ids,
        the packed (ts, hb) words (``_pack_th``), own_hb, then the F
        per-round send flags (0/1).
      cur_key/cur_p: u32/i32[N, K] — accumulators' initial value (the
        receiver's current table keys, models/overlay.py).
      masks: i32[F] — this tick's XOR masks ``m_f`` (all in [1, N)).
      t, seed: the clock (i32) and hash seed (u32).

    Returns ``(keymax u32[N, K], p_acc i32[N, K], recv i32[N])`` with
    NO receiver-side ``proc`` gating — the caller selects
    ``where(proc, result, initial)`` (bit-equal because an invalid
    receiver's accumulator is simply discarded).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, c = payload.shape
    f_rounds = int(masks.shape[0])
    assert c == 2 * k + 1 + f_rounds, (c, k, f_rounds)
    b = min(block_rows, n)
    assert n % b == 0 and b & (b - 1) == 0 and b >= 8, (n, b)
    nb = n // b

    i32 = jnp.int32
    sp = jnp.concatenate([
        jnp.asarray([t], i32).reshape(1),
        seed.astype(i32).reshape(1),
        masks.astype(i32).reshape(f_rounds)])

    row_block = lambda i, sp_ref: (i, 0)

    def payload_spec(fi):
        return pl.BlockSpec(
            (b, c),
            lambda i, sp_ref, fi=fi: (i ^ (sp_ref[2 + fi] // b), 0),
            memory_space=pltpu.VMEM)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[payload_spec(fi) for fi in range(f_rounds)] + [
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((b, k), row_block, memory_space=pltpu.VMEM),
            pl.BlockSpec((b, 2 * k), row_block, memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((b, c), i32)],
    )
    kmax, pacc_recv = pl.pallas_call(
        functools.partial(_kernel, b, c, k, f_rounds, t_remove),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((n, k), jnp.uint32),
            jax.ShapeDtypeStruct((n, 2 * k), i32),
        ],
        interpret=interpret,
    )(sp, *([payload] * f_rounds), cur_key, cur_p)
    return kmax, pacc_recv[:, :k], pacc_recv[:, k]
