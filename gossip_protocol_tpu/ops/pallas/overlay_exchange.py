"""Fused Pallas TPU kernel: the overlay tick's whole per-(N, K) phase.

The overlay tick (models/overlay.py) is, per exchange round ``f``:
permute the payload matrix by ``x[i ^ m_f]`` and fold the incoming
view into the receiver's table; then consume JOINREP/JOINREQ, extract
the winners, and run staleness detection.  The XLA formulation pays
two HIGHEST-precision f32 permutation matmuls of O(sqrt(N))
contraction depth per round — O(N^1.5 · K) FLOPs that dominate at the
1M-peer BASELINE config — plus a long chain of (N, K) elementwise ops
whose intermediates round-trip HBM.  This kernel does the entire
per-(N, K) phase in one launch:

* the high bits of ``i ^ m`` are folded into the grid's **block index
  map** (block ``i`` DMAs source block ``i ^ (m >> lgB)`` — the mask is
  a scalar-prefetch argument, so the DMA address is known before the
  body runs);
* the low bits are a **butterfly network in VMEM**: for each set bit
  ``j`` of ``m % B``, rows swap with their ``r ^ 2^j`` partner — a
  static rotate + select, predicated with ``pl.when`` so unset bits
  cost nothing, exact integer moves (no bf16-truncation hazard);
* because tables are slotted by the global epoch map (models/overlay.py
  design), each round's merge is a **lane-aligned lexicographic
  (key, payload) max** on (B, K) — no slot-match product — plus a
  one-hot merge of the partner's self-entry;
* accumulator init (the receiver's own keys), receiver ``proc``
  gating, the JOINREP broadcast merge, the JOINREQ row-0 aggregate
  merge, winner extraction, TREMOVE staleness detection, and the
  per-row metric counts all run in the same launch.

Everything the kernel needs beyond the (N, K) tables rides in lane
padding or tiny replicated blocks: a (N, K) int32 array is stored
lane-padded to 128 on TPU anyway, so the aux columns (own_hb, the
packed proc/ops/jrep bits, the F send flags) extend the ids plane to
(N, K+2+F) at zero extra HBM, and the per-row counters ride lanes
[K, K+6) of the ts output plane.  Per tick the kernel reads each
table plane 1+F times and writes the three result planes once; there
are no matmuls at all.

Semantics are bit-identical to the XLA phases in models/overlay.py
(same ``_pack_key``/``_pack_th``/``_slot_of``/schedule contract; the
lexicographic max is order-free, so fusing the phases cannot change
any winner).  Differentially tested in tests/test_overlay_pallas.py.

Mosaic workarounds (observed on v5e): ``jnp.maximum`` on uint32
vectors does not legalize (``arith.maxui``), so the lexicographic
merge sticks to compare+select.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat.jaxapi import shape_dtype_struct

#: per-row metric counters packed into the ts output's spare lanes
#: [K, K+N_COUNTERS): recv, removals, false_removals, victim_slots,
#: adds, view_slots
N_COUNTERS = 6


def _roll_rows(x, shift: int):
    """Circular roll along axis 0 by a static shift (concat of static
    slices — lowers unconditionally in Mosaic and interpret mode)."""
    s = shift % x.shape[0]
    if s == 0:
        return x
    return jnp.concatenate([x[-s:], x[:-s]], axis=0)


def _kernel(b: int, w_cols: int, k: int, f_rounds: int, t_remove: int,
            churn_lo: int, churn_span: int, never: int,
            # scalar prefetch (shard-INVARIANT — index maps are
            # evaluated with replicated loop indices, so shard-varying
            # values must not ride here): [t, seed, victim_lo,
            #   victim_hi, fail_tick, rejoin_after, churn_thr,
            #   churn_after, mlo_0 .. mlo_{F-1}, m_0 .. m_{F-1}]
            # (mlo = shard-local mask bits for the block index map;
            #  m = the global mask for partner identity — identical
            #  on a single device)
            sp_ref,
            # inputs
            *refs):
    from ...config import INTRODUCER
    from ...models.overlay import (ID_MASK, SLOT_EPOCH, _SALT_CHURN,
                                   _SALT_CHURN_TICK, _pack_key,
                                   _pack_th, _slot_of)
    from ...utils.hash32 import mix32

    ia_id = refs[0]                     # (B, W) identity idsaux
    pw_id = refs[1]                     # (B, K) identity packed (ts, hb)
    ia_x = refs[2:2 + f_rounds]         # per-round XOR-mapped idsaux
    pw_x = refs[2 + f_rounds:2 + 2 * f_rounds]
    intro_ref = refs[2 + 2 * f_rounds]  # (8, K) replicated small input
    rs_ref = refs[3 + 2 * f_rounds]     # SMEM (1,): global id of local
    #                                     row 0 (shard-varying, so it
    #                                     cannot ride scalar prefetch)
    ids_out, hb_out, tsc_out, wa_scr, wp_scr = refs[4 + 2 * f_rounds:]

    i_blk = pl.program_id(0)
    t = sp_ref[0]
    seed = sp_ref[1].astype(jnp.uint32)
    victim_lo = sp_ref[2]
    victim_hi = sp_ref[3]
    fail_tick = sp_ref[4]
    rejoin_after = sp_ref[5]
    churn_thr = sp_ref[6].astype(jnp.uint32)
    churn_after = sp_ref[7]
    row_start = rs_ref[0]

    rbits = jax.lax.broadcasted_iota(jnp.int32, (b, 1), 0)
    rows = row_start + i_blk * b + rbits           # (B, 1) global rows
    kk = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)
    lgb = b.bit_length() - 1
    slot_ep = (t // SLOT_EPOCH).astype(jnp.uint32)

    # ---- own state + accumulator init ------------------------------
    my = ia_id[:]
    my_ids = my[:, :k]
    bits = my[:, k + 1:k + 2]
    proc_r = (bits & 1) > 0
    ops_r = (bits & 2) > 0
    jrep_r = (bits & 4) > 0
    my_p = jnp.where(my_ids >= 0, pw_id[:], 0)
    my_ts = (my_p >> 12) - 1
    kmax = jnp.where(my_ids >= 0, _pack_key(my_ids, my_ts),
                     jnp.uint32(0))
    pacc = my_p
    recv = jnp.zeros((b, 1), jnp.int32)

    def lex(kmax, pacc, key_c, p_c):
        better = (key_c > kmax) | ((key_c == kmax) & (p_c > pacc))
        return (jnp.where(better, key_c, kmax),
                jnp.where(better, p_c, pacc))

    # ---- F exchange rounds -----------------------------------------
    for fi in range(f_rounds):
        m_lo = sp_ref[8 + fi]                # shard-local mask bits
        m = sp_ref[8 + f_rounds + fi]        # global mask (partner id)
        # butterfly the local mask's low bits, predicated per bit
        wa_scr[:] = ia_x[fi][:]
        wp_scr[:] = pw_x[fi][:]
        for j in range(lgb):
            s = 1 << j

            @pl.when(((m_lo >> j) & 1) == 1)
            def _swap(s=s, j=j):
                sel = ((rbits >> j) & 1) == 0
                cur_a = wa_scr[:]
                wa_scr[:] = jnp.where(sel, _roll_rows(cur_a, -s),
                                      _roll_rows(cur_a, s))
                cur_p = wp_scr[:]
                wp_scr[:] = jnp.where(sel, _roll_rows(cur_p, -s),
                                      _roll_rows(cur_p, s))
        wa = wa_scr[:]
        wp = wp_scr[:]

        flag = wa[:, k + 2 + fi:k + 3 + fi] > 0          # (B, 1)
        ok = flag & proc_r
        in_ids = wa[:, :k]
        in_p = wp
        in_ts = (in_p >> 12) - 1
        valid = ok & (in_ids >= 0) & (t - in_ts < t_remove) \
            & (in_ids != rows)
        key = jnp.where(valid, _pack_key(in_ids, in_ts),
                        jnp.uint32(0))
        kmax, pacc = lex(kmax, pacc, key, jnp.where(valid, in_p, 0))

        if t_remove > 1:                 # partner self-entry (age 1)
            partner = rows ^ m
            psl = _slot_of(seed, slot_ep, partner, k)
            e_ts = jnp.zeros_like(partner) + (t - 1)
            pkey = jnp.where(ok, _pack_key(partner, e_ts),
                             jnp.uint32(0))
            pp = jnp.where(ok, _pack_th(e_ts, wa[:, k:k + 1]), 0)
            match = psl == kk
            kmax, pacc = lex(kmax, pacc,
                             jnp.where(match, pkey, jnp.uint32(0)),
                             jnp.where(match, pp, 0))
        recv = recv + ok.astype(jnp.int32)

    # ---- JOINREP: the introducer's broadcast view ------------------
    bc_ids = intro_ref[0:1, :]                       # (1, K)
    bc_p = intro_ref[1:2, :]
    bc_ts = (bc_p >> 12) - 1
    j_valid = jrep_r & (bc_ids >= 0) & (t - bc_ts < t_remove) \
        & (bc_ids != rows)
    jkey = jnp.where(j_valid, _pack_key(bc_ids, bc_ts),
                     jnp.uint32(0))
    kmax, pacc = lex(kmax, pacc, jkey, jnp.where(j_valid, bc_p, 0))
    if t_remove > 1:                     # the introducer's self-entry
        intro_vec = jnp.zeros_like(rows) + INTRODUCER
        islot = _slot_of(seed, slot_ep, intro_vec, k)
        e_ts = jnp.zeros_like(rows) + (t - 1)
        iok = jrep_r & (rows != INTRODUCER)
        ikey = jnp.where(iok, _pack_key(intro_vec, e_ts),
                         jnp.uint32(0))
        ip = jnp.where(iok, _pack_th(e_ts, intro_ref[2:3, 0:1]), 0)
        imatch = islot == kk
        kmax, pacc = lex(kmax, pacc,
                         jnp.where(imatch, ikey, jnp.uint32(0)),
                         jnp.where(imatch, ip, 0))

    # ---- JOINREQ aggregates into the introducer's row --------------
    is_r0 = rows == INTRODUCER
    q_kf = intro_ref[3:4, :].astype(jnp.uint32)
    q_pf = intro_ref[4:5, :]
    kmax, pacc = lex(kmax, pacc,
                     jnp.where(is_r0, q_kf, jnp.uint32(0)),
                     jnp.where(is_r0, q_pf, 0))

    # ---- winner extraction + staleness detection -------------------
    ids1 = jnp.where(kmax > 0,
                     (kmax & jnp.uint32(ID_MASK)).astype(jnp.int32), -1)
    ts1 = jnp.where(kmax > 0, (pacc >> 12) - 1, 0)
    hb1 = jnp.where(kmax > 0, (pacc & 0xFFF) - 1, 0)
    stale = (ids1 >= 0) & (t - ts1 >= t_remove) & ops_r
    ids2 = jnp.where(stale, -1, ids1)
    hb2 = jnp.where(stale, 0, hb1)
    ts2 = jnp.where(stale, 0, ts1)

    # ---- subject fail/rejoin (closed-form schedule, in-kernel) -----
    subj = jnp.clip(ids1, 0)
    subj_u = subj.astype(jnp.uint32)
    churned = (mix32(seed, subj_u, np.uint32(_SALT_CHURN)) < churn_thr) \
        & (subj != INTRODUCER)
    churn_fail = churn_lo + (mix32(seed, subj_u, np.uint32(_SALT_CHURN_TICK))
                             % np.uint32(churn_span)).astype(jnp.int32)
    scripted = jnp.where((subj >= victim_lo) & (subj < victim_hi),
                         fail_tick, never)
    fail = jnp.where(churn_thr > 0,
                     jnp.where(churned, churn_fail, never), scripted)
    after = jnp.where(churn_thr > 0, churn_after, rejoin_after)
    rejoin = jnp.where((fail != never) & (after != never), fail + after,
                       never)
    subj_failed = (t > fail) & (t <= rejoin)

    # ---- outputs: result planes + per-row counters -----------------
    ids_out[:] = ids2
    hb_out[:] = hb2
    ctr = jnp.concatenate([
        recv,
        stale.sum(1, keepdims=True).astype(jnp.int32),
        (stale & ~subj_failed).sum(1, keepdims=True).astype(jnp.int32),
        ((ids2 >= 0) & subj_failed & ~stale).sum(1, keepdims=True)
        .astype(jnp.int32),
        ((ids1 != my_ids) & (ids1 >= 0)).sum(1, keepdims=True)
        .astype(jnp.int32),
        (ids2 >= 0).sum(1, keepdims=True).astype(jnp.int32),
    ], axis=1)                                        # (B, N_COUNTERS)
    lane = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)
    ctr_padded = jnp.concatenate(
        [ctr, jnp.zeros((b, k - N_COUNTERS), jnp.int32)], axis=1)
    tsc_out[:] = jnp.concatenate([ts2, ctr_padded], axis=1)
    del lane


@functools.partial(jax.jit,
                   static_argnames=("k", "t_remove", "churn_lo",
                                    "churn_span", "block_rows",
                                    "interpret", "vma"))
def fused_overlay_tick(idsaux, pw, intro, masks, scalars, *,
                       k: int, t_remove: int, churn_lo: int,
                       churn_span: int, block_rows: int = 512,
                       interpret: bool | None = None,
                       masks_local=None, row_start=None,
                       aux_rounds=None, pw_rounds=None,
                       vma: tuple = ()):
    """The overlay tick's whole (N, K) phase in one Pallas launch.

    Args:
      idsaux: i32[Nl, K+2+F] — lanes [0, K) the (post-wipe) view ids,
        lane K own_hb, lane K+1 the packed proc|ops<<1|jrep<<2 bits,
        lanes [K+2, K+2+F) the per-round send flags.  Stored
        lane-padded to 128 on TPU anyway, so the aux lanes are free.
        Nl = the locally-held rows (= N on a single device).
      pw: i32[Nl, K] — the packed (ts, hb) payload words (_pack_th; 0
        for empty slots is fine, ids gate validity).
      intro: i32[8, K] — row 0 the introducer's ids, row 1 its packed
        words, row 2 lane 0 its own_hb, row 3 the JOINREQ per-slot key
        aggregate (uint32 bits), row 4 the matching packed payloads.
      masks: i32[F] — this tick's GLOBAL XOR masks (partner identity).
      scalars: i32[8] — [t, seed, victim_lo, victim_hi, fail_tick,
        rejoin_after, churn_thr (uint32 bits), churn_after].
      churn_lo/churn_span: static schedule constants (cfg.total_ticks
        derived — the run cache is keyed on them).

    Sharded execution (inside ``shard_map``): the XOR exchange
    decomposes as ``i ^ m = (s ^ m_hi)*Nl + (il ^ m_lo)`` — the comm
    routes the shard bits by ppermuting whole planes per round
    (``aux_rounds``/``pw_rounds``, each i32[F, Nl, ...]), while this
    kernel applies only the local bits ``masks_local = m % Nl`` in its
    block index map / butterfly.  ``row_start`` is the global id of
    local row 0 (receiver identity for the per-receiver tie hash,
    partner ids, and the introducer row match).  All four default to
    the single-device identity.

    Returns ``(ids2 i32[Nl, K], hb2 i32[Nl, K], ts2 i32[Nl, K],
    counters i32[Nl, N_COUNTERS])`` — counters columns are per-row
    [recv, removals, false_removals, victim_slots, adds, view_slots].
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, w_cols = idsaux.shape
    f_rounds = int(masks.shape[0])
    assert w_cols == k + 2 + f_rounds, (w_cols, k, f_rounds)
    assert k >= N_COUNTERS
    if masks_local is None:
        masks_local = masks % n
    if row_start is None:
        row_start = jnp.int32(0)
    if aux_rounds is None:
        aux_rounds = jnp.broadcast_to(idsaux, (f_rounds,) + idsaux.shape)
    if pw_rounds is None:
        pw_rounds = jnp.broadcast_to(pw, (f_rounds,) + pw.shape)
    # each of the 1+F bindings of the two table planes double-buffers a
    # (B, <=128)-lane block in VMEM; at F > 4 a 512-row block exceeds
    # the 16 MB scoped budget (measured: 16.14M at F=8), so halve it
    b = min(block_rows if f_rounds <= 4 else block_rows // 2, n)
    assert n % b == 0 and b & (b - 1) == 0 and b >= 8, (n, b)
    nb = n // b

    i32 = jnp.int32
    sp = jnp.concatenate([scalars.astype(i32),
                          masks_local.astype(i32), masks.astype(i32)])
    rs = jnp.reshape(row_start, (1,)).astype(i32)

    row_block_w = pl.BlockSpec((b, w_cols), lambda i, sp_ref: (i, 0),
                               memory_space=pltpu.VMEM)
    row_block_k = pl.BlockSpec((b, k), lambda i, sp_ref: (i, 0),
                               memory_space=pltpu.VMEM)

    def xor_spec(fi, cols):
        return pl.BlockSpec(
            (b, cols),
            lambda i, sp_ref, fi=fi: (i ^ (sp_ref[8 + fi] // b), 0),
            memory_space=pltpu.VMEM)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[row_block_w, row_block_k]
        + [xor_spec(fi, w_cols) for fi in range(f_rounds)]
        + [xor_spec(fi, k) for fi in range(f_rounds)]
        + [pl.BlockSpec((8, k), lambda i, sp_ref: (0, 0),
                        memory_space=pltpu.VMEM),
           pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[
            row_block_k,
            row_block_k,
            pl.BlockSpec((b, 2 * k), lambda i, sp_ref: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((b, w_cols), i32),
                        pltpu.VMEM((b, k), i32)],
    )
    from ...models.overlay import SLOT_EPOCH  # noqa: F401  (doc pointer)
    from ...state import NEVER
    ids2, hb2, tsc = pl.pallas_call(
        functools.partial(_kernel, b, w_cols, k, f_rounds, t_remove,
                          churn_lo, churn_span, int(NEVER)),
        grid_spec=grid_spec,
        out_shape=[
            shape_dtype_struct((n, k), i32, vma=vma),
            shape_dtype_struct((n, k), i32, vma=vma),
            shape_dtype_struct((n, 2 * k), i32, vma=vma),
        ],
        interpret=interpret,
    )(sp, idsaux, pw, *[aux_rounds[fi] for fi in range(f_rounds)],
      *[pw_rounds[fi] for fi in range(f_rounds)], intro, rs)
    return ids2, hb2, tsc[:, :k], tsc[:, k:k + N_COUNTERS]
