"""Multi-tick Pallas megakernel: S overlay ticks per launch, state in VMEM.

Round-2 profiling showed the overlay tick's wall-clock at N <= 16k is
dominated by *fixed* per-launch cost, not work: one Pallas launch costs
~300-400 us regardless of size (measured: N=512 single-block kernel
316 us, N=4096 430 us), and the non-kernel XLA vector phases add another
~500 us of per-op dispatch floor.  At N=4096 that caps the simulator at
~1,100 ticks/s while the chip is ~99% idle — exactly the gap between
the per-tick hot loop the reference runs on a CPU
(/root/reference/Application.cpp:99-104) and BASELINE.md's >=10k
ticks/s north star.

This kernel removes the floor by running ``MEGA_TICKS`` whole protocol
ticks per launch with the entire world state resident in VMEM:

* **One state plane.**  ids, the packed (ts, hb) payload words, and all
  per-peer vectors (in_group, own_hb, joinreq, joinrep, the F send
  flags) plus the loop-invariant schedule columns (start/fail/rejoin
  ticks, power-law out-degree) share a single (N, 2K+16) i32 plane —
  2K+16 <= 128 lanes, so the whole state is one native VMEM tile wide
  and the per-tick HBM round-trip disappears entirely.
* **Everything in-kernel.**  Each tick runs the full pipeline of
  models/overlay.py: churn wipe, join/start decisions, the JOINREQ
  slot aggregation at the introducer, F XOR exchange rounds (full
  in-VMEM butterfly — no grid, so every mask bit is a roll+select),
  the lane-aligned lexicographic merges, JOINREP/JOINREQ handling,
  winner extraction, TREMOVE staleness detection, the SLOT_EPOCH
  re-slot pass, drop-masked send flags, and the per-tick metric
  reductions (stored one row per tick).
* **Bounded live set.**  Mosaic keeps every live (N, lanes) value in
  VMEM, so a tick written as one flat dataflow spills ~60 whole planes
  (measured 126 MB of allocator spill slots at N=4096 — over the
  128 MB v5e VMEM).  The tick is therefore phased: the butterflies
  write F whole-plane scratches, and all per-row logic (decisions,
  merges, joins, extraction, detection, metrics) runs in a fori loop
  over row CHUNKS whose live values are (B, lanes)-sized.
* **Same bits.**  All randomness is the same counter-hash streams
  (utils/hash32.mix32) evaluated in-kernel; the per-launch XOR masks
  ride the scalar-prefetch vector.  The trajectory is bit-identical to
  the XLA path (differentially tested in tests/test_overlay_mega.py),
  so the megakernel is a pure scheduling optimization.

Scope: single-device, power-of-two N with 2*K+16 <= 128 and
N <= MEGA_N_LIMIT (the hardware-verified envelope).  Larger N keeps
the per-tick fused kernel (overlay_exchange.py); the sharded mesh
path uses that kernel under shard_map.

The per-tick metric ``live_uncovered`` needs a cross-peer histogram
the kernel does not compute; the megakernel path reports -1 (the
"not tracked" sentinel already used above COVERAGE_N_LIMIT) and
final-state coverage is still validated host-side
(models/overlay.py OverlayResult.final_coverage).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tpu_compiler_params

#: protocol ticks per launch (the launch-overhead amortization factor).
#: One slot epoch per launch keeps at most one re-slot pass per chunk.
MEGA_TICKS = 16

#: row-chunk height of the per-row phase (bounds the live-value set)
CHUNK_ROWS = 1024

#: aux lane offsets, relative to lane 2K
_IN_GROUP = 0
_OWN_HB = 1
_JOINREQ = 2
_JOINREP = 3
_SF = 4          # send flags, lanes [_SF, _SF + F), F <= 8
_START = 12
_FAIL = 13
_REJOIN = 14
_DEG = 15
AUX_LANES = 16

#: scalar-prefetch layout (masks follow, F per tick)
_SP_T0 = 0
_SP_SEED = 1
_SP_VLO = 2
_SP_VHI = 3
_SP_FTICK = 4
_SP_RAFTER = 5
_SP_CTHR = 6
_SP_CAFTER = 7
_SP_DROP_ON = 8
_SP_DROP_OPEN = 9
_SP_DROP_CLOSE = 10
_SP_DROP_THR = 11
_SP_FAIL0 = 12
_SP_REJOIN0 = 13
_SP_NSCALARS = 14

#: metric column layout of the (S, 128) output
MET_IN_GROUP = 0
MET_VIEW = 1
MET_ADDS = 2
MET_REMOVALS = 3
MET_FALSE_REMOVALS = 4
MET_VICTIM = 5
MET_SENT = 6
MET_RECV = 7

_SIGN = np.uint32(0x80000000)


def _umax0(x):
    """Column-wise uint32 max over sublanes via the sign-flip trick —
    Mosaic legalizes signed i32 reductions but not unsigned ones."""
    s = (x ^ _SIGN).astype(jnp.int32)
    return (s.max(axis=0, keepdims=True).astype(jnp.uint32)) ^ _SIGN


def _sum_all(x):
    """(N, C) -> (1, 1) i32 full reduction."""
    return x.astype(jnp.int32).sum(axis=1, keepdims=True) \
        .sum(axis=0, keepdims=True)


def _lex(kmax, pacc, key_c, p_c):
    """Lexicographic (key, payload) max — associative and commutative."""
    better = (key_c > kmax) | ((key_c == kmax) & (p_c > pacc))
    return (jnp.where(better, key_c, kmax),
            jnp.where(better, p_c, pacc))


def _kernel(n: int, k: int, f_rounds: int, s_ticks: int, t_remove: int,
            churn_lo: int, churn_span: int, never: int, can_rejoin: bool,
            powerlaw: bool,
            sp_ref, st_in, st_out, met_out, *w_refs):
    from ...config import INTRODUCER
    from ...models.overlay import (ID_MASK, SLOT_EPOCH, _SALT_CHURN,
                                   _SALT_CHURN_TICK, _SALT_GOSSIP_DROP,
                                   _SALT_JOINREP_DROP, _SALT_JOINREQ_DROP,
                                   _pack_key, _pack_th, _slot_of)
    from ...utils.hash32 import mix32

    a = 2 * k                                   # aux lane base
    w = a + AUX_LANES
    b = min(CHUNK_ROWS, n)                      # row-chunk height
    n_chunks = n // b
    seed = sp_ref[_SP_SEED].astype(jnp.uint32)
    churn_thr = sp_ref[_SP_CTHR].astype(jnp.uint32)
    drop_thr = sp_ref[_SP_DROP_THR].astype(jnp.uint32)
    i32 = jnp.int32

    rows_n = jax.lax.broadcasted_iota(i32, (n, 1), 0)
    rows_b0 = jax.lax.broadcasted_iota(i32, (b, 1), 0)
    kk_n = jax.lax.broadcasted_iota(i32, (n, k), 1)
    kk_b = jax.lax.broadcasted_iota(i32, (b, k), 1)
    fis_b = jax.lax.broadcasted_iota(i32, (b, f_rounds), 1)

    st_out[:] = st_in[:]

    def tick(s, _):
        t = sp_ref[_SP_T0] + s
        tu = t.astype(jnp.uint32)
        slot_ep = (t // SLOT_EPOCH).astype(jnp.uint32)

        # introducer scalars (start_of(INTRODUCER) == 0)
        fail0 = sp_ref[_SP_FAIL0]
        rejoin0 = sp_ref[_SP_REJOIN0]
        failed0 = (t > fail0) & (t <= rejoin0)
        proc0 = (t > 0) & jnp.logical_not(failed0)

        # ---- phase A0 (whole plane): churn wipe --------------------
        # (models/overlay.py "churn wipe"); freezes the send-tick
        # payload — post-wipe tables + own_hb — in the state plane
        if can_rejoin:
            st = st_out[:]
            rejoining_n = t == st[:, a + _REJOIN:a + _REJOIN + 1]
            keep = ~rejoining_n
            st_out[:] = jnp.concatenate(
                [jnp.where(keep, st[:, 0:k], -1),
                 jnp.where(keep, st[:, k:a], 0),
                 jnp.where(keep, st[:, a:a + 2], 0),
                 st[:, a + 2:]], axis=1)

        # ---- phase A1 (whole plane): JOINREQ slot aggregates -------
        # at the introducer (addMember, MP1Node.cpp:265-280) — the
        # overlay's dense one-hot max as a sublane reduction
        st = st_out[:]
        jreq_n = (st[:, a + _JOINREQ:a + _JOINREQ + 1] > 0) & proc0
        q_slot = _slot_of(seed, slot_ep, rows_n, k)
        q_ok = jreq_n & (rows_n != INTRODUCER)
        q_key = jnp.where(q_ok,
                          _pack_key(rows_n, jnp.zeros_like(rows_n) + t),
                          jnp.uint32(0))
        q_kf = _umax0(jnp.where(q_slot == kk_n, q_key, jnp.uint32(0)))
        q_pf = jnp.where(q_kf > 0, _pack_th(t, 1), 0)        # (1, K)
        jreq_cnt = _sum_all(jreq_n)

        # the introducer's payload row (JOINREP broadcast source) —
        # snapshotted before any chunk overwrites row 0
        bc = st_out[INTRODUCER:INTRODUCER + 1, :]            # (1, W)

        # ---- phase A2 (whole plane): F XOR butterflies -------------
        # The tick's wall-clock at mega sizes is per-vector-op
        # overhead (measured ~flat in N from 512 to 4096), so each
        # bit level is ONE group-roll concat — x[r ^ s] equals a
        # roll-by-s within each 2s-row group — and pl.when skips
        # unset mask bits at scalar-branch cost.
        for fi in range(f_rounds):
            m = sp_ref[_SP_NSCALARS + s * f_rounds + fi]
            w_refs[fi][:] = st_out[:]
            for j in range(n.bit_length() - 1):
                sh = 1 << j

                @pl.when(((m >> j) & 1) == 1)
                def _swap(fi=fi, sh=sh):
                    cur = w_refs[fi][:]
                    z = cur.reshape(n // (2 * sh), 2 * sh, w)
                    w_refs[fi][:] = jnp.concatenate(
                        [z[:, sh:], z[:, :sh]], axis=1).reshape(n, w)

        # ---- phase B (row chunks): the whole per-row pipeline ------
        met_out[pl.ds(s, 1), :] = jnp.zeros((1, 128), i32)

        def chunk(c, _):
            r0 = c * b
            rows = rows_b0 + r0
            rows_u = rows.astype(jnp.uint32)
            is_intro = rows == INTRODUCER
            st = st_out[pl.ds(r0, b), :]
            ids0 = st[:, 0:k]
            pw0 = st[:, k:a]
            in_group0 = st[:, a + _IN_GROUP:a + _IN_GROUP + 1] > 0
            own_hb0 = st[:, a + _OWN_HB:a + _OWN_HB + 1]
            joinreq_c = st[:, a + _JOINREQ:a + _JOINREQ + 1] > 0
            joinrep_c = st[:, a + _JOINREP:a + _JOINREP + 1] > 0
            start = st[:, a + _START:a + _START + 1]
            fail = st[:, a + _FAIL:a + _FAIL + 1]
            rejoin = st[:, a + _REJOIN:a + _REJOIN + 1]

            failed = (t > fail) & (t <= rejoin)
            proc = (t > start) & ~failed
            rejoining = (t == rejoin) if can_rejoin \
                else jnp.zeros_like(is_intro)

            # vector decisions (models/overlay.py "vector decisions")
            jrep = joinrep_c & proc
            in_group = in_group0 | jrep
            starting = (t == start) | rejoining
            in_group = in_group | (starting & is_intro)
            ops = proc & in_group
            own_hb = own_hb0 + ops.astype(i32)

            # accumulator init
            ts0 = (pw0 >> 12) - 1
            kmax = jnp.where(ids0 >= 0, _pack_key(ids0, ts0),
                             jnp.uint32(0))
            pacc = pw0
            recv = jnp.zeros((b, 1), i32)

            # F exchange rounds: lane-aligned lexicographic merges
            for fi in range(f_rounds):
                m = sp_ref[_SP_NSCALARS + s * f_rounds + fi]
                wv = w_refs[fi][pl.ds(r0, b), :]
                in_ids = wv[:, 0:k]
                in_p = wv[:, k:a]
                in_ts = (in_p >> 12) - 1
                own_p = wv[:, a + _OWN_HB:a + _OWN_HB + 1]
                flag = wv[:, a + _SF + fi:a + _SF + fi + 1] > 0
                ok = flag & proc
                valid = ok & (in_ids >= 0) & (t - in_ts < t_remove) \
                    & (in_ids != rows)
                key = jnp.where(valid, _pack_key(in_ids, in_ts),
                                jnp.uint32(0))
                kmax, pacc = _lex(kmax, pacc, key,
                                  jnp.where(valid, in_p, 0))
                if t_remove > 1:         # partner self-entry (age 1)
                    partner = rows ^ m
                    psl = _slot_of(seed, slot_ep, partner, k)
                    e_ts = jnp.zeros_like(partner) + (t - 1)
                    pkey = jnp.where(ok, _pack_key(partner, e_ts),
                                     jnp.uint32(0))
                    pp = jnp.where(ok, _pack_th(e_ts, own_p), 0)
                    match = psl == kk_b
                    kmax, pacc = _lex(kmax, pacc,
                                      jnp.where(match, pkey, jnp.uint32(0)),
                                      jnp.where(match, pp, 0))
                recv = recv + ok.astype(i32)

            # JOINREP: the introducer's broadcast view
            bc_ids = bc[:, 0:k]
            bc_p = bc[:, k:a]
            bc_ts = (bc_p >> 12) - 1
            j_valid = jrep & (bc_ids >= 0) & (t - bc_ts < t_remove) \
                & (bc_ids != rows)
            jkey = jnp.where(j_valid, _pack_key(bc_ids, bc_ts),
                             jnp.uint32(0))
            kmax, pacc = _lex(kmax, pacc, jkey,
                              jnp.where(j_valid, bc_p, 0))
            if t_remove > 1:             # the introducer's self-entry
                intro_vec = jnp.zeros_like(rows) + INTRODUCER
                islot = _slot_of(seed, slot_ep, intro_vec, k)
                e_ts = jnp.zeros_like(rows) + (t - 1)
                iok = jrep & ~is_intro
                ikey = jnp.where(iok, _pack_key(intro_vec, e_ts),
                                 jnp.uint32(0))
                ip = jnp.where(iok,
                               _pack_th(e_ts,
                                        bc[:, a + _OWN_HB:a + _OWN_HB + 1]),
                               0)
                imatch = islot == kk_b
                kmax, pacc = _lex(kmax, pacc,
                                  jnp.where(imatch, ikey, jnp.uint32(0)),
                                  jnp.where(imatch, ip, 0))

            # JOINREQ aggregates into the introducer's row
            kmax, pacc = _lex(kmax, pacc,
                              jnp.where(is_intro, q_kf, jnp.uint32(0)),
                              jnp.where(is_intro, q_pf, 0))

            # winner extraction + staleness detection
            ids1 = jnp.where(kmax > 0,
                             (kmax & jnp.uint32(ID_MASK)).astype(i32), -1)
            ts1 = jnp.where(kmax > 0, (pacc >> 12) - 1, 0)
            hb1 = jnp.where(kmax > 0, (pacc & 0xFFF) - 1, 0)
            stale = (ids1 >= 0) & (t - ts1 >= t_remove) & ops
            ids2 = jnp.where(stale, -1, ids1)
            pw2 = jnp.where(stale | (ids1 < 0), 0, _pack_th(ts1, hb1))

            # subject fail/rejoin (closed-form schedule, in-kernel)
            subj = jnp.where(ids1 >= 0, ids1, 0)
            subj_u = subj.astype(jnp.uint32)
            churned = (mix32(seed, subj_u, np.uint32(_SALT_CHURN))
                       < churn_thr) & (subj != INTRODUCER)
            churn_fail = churn_lo + (
                mix32(seed, subj_u, np.uint32(_SALT_CHURN_TICK))
                % np.uint32(churn_span)).astype(i32)
            scripted = jnp.where(
                (subj >= sp_ref[_SP_VLO]) & (subj < sp_ref[_SP_VHI]),
                sp_ref[_SP_FTICK], never)
            s_fail = jnp.where(churn_thr > 0,
                               jnp.where(churned, churn_fail, never),
                               scripted)
            s_after = jnp.where(churn_thr > 0, sp_ref[_SP_CAFTER],
                                sp_ref[_SP_RAFTER])
            s_rejoin = jnp.where((s_fail != never) & (s_after != never),
                                 s_fail + s_after, never)
            subj_failed = (t > s_fail) & (t <= s_rejoin)

            # dissemination: next tick's send flags
            active = (sp_ref[_SP_DROP_ON] > 0) \
                & (t > sp_ref[_SP_DROP_OPEN]) \
                & (t <= sp_ref[_SP_DROP_CLOSE])
            gdrop = mix32(seed, tu, rows_u, fis_b.astype(jnp.uint32),
                          np.uint32(_SALT_GOSSIP_DROP)) < drop_thr
            sf_next = ops & ~(active & gdrop)
            if powerlaw:
                deg = st[:, a + _DEG:a + _DEG + 1]
                sf_next = sf_next & (fis_b < deg)
            joinreq_new = starting & ~is_intro
            qdrop = mix32(seed, tu, rows_u,
                          np.uint32(_SALT_JOINREQ_DROP)) < drop_thr
            pdrop = mix32(seed, tu, rows_u,
                          np.uint32(_SALT_JOINREP_DROP)) < drop_thr
            joinreq_sent = joinreq_new & ~(active & qdrop)
            jreq = joinreq_c & proc0
            joinrep_sent = jreq & ~(active & pdrop)
            live_hold = ~proc & ~failed
            joinreq_next = joinreq_sent \
                | (joinreq_c & ~proc0 & jnp.logical_not(failed0))
            joinrep_next = joinrep_sent | (joinrep_c & live_hold)

            # metrics: accumulate into this tick's row
            # one packed (1, 8) accumulate; lane order must match
            # the MET_* column constants
            delta = jnp.concatenate([
                _sum_all(in_group),
                _sum_all(ids2 >= 0),
                _sum_all((ids1 != ids0) & (ids1 >= 0)),
                _sum_all(stale),
                _sum_all(stale & ~subj_failed),
                _sum_all((ids2 >= 0) & subj_failed & ~stale),
                _sum_all(sf_next) + _sum_all(joinreq_sent)
                + _sum_all(joinrep_sent),
                _sum_all(recv) + _sum_all(jrep),
            ], axis=1)
            met_out[pl.ds(s, 1), 0:8] = met_out[pl.ds(s, 1), 0:8] + delta

            # write the end-of-tick chunk
            sf_i = sf_next.astype(i32)
            if f_rounds < 8:
                sf_i = jnp.concatenate(
                    [sf_i, jnp.zeros((b, 8 - f_rounds), i32)], axis=1)
            st_out[pl.ds(r0, b), :] = jnp.concatenate(
                [ids2, pw2, in_group.astype(i32), own_hb,
                 joinreq_next.astype(i32), joinrep_next.astype(i32),
                 sf_i, st[:, a + _START:]], axis=1)
            return ()

        jax.lax.fori_loop(0, n_chunks, chunk, (), unroll=False)
        # JOINREQs consumed by the introducer count as receives
        # (jrep receives are accumulated per chunk above)
        met_out[pl.ds(s, 1), pl.ds(MET_RECV, 1)] = \
            met_out[pl.ds(s, 1), pl.ds(MET_RECV, 1)] + jreq_cnt

        # ---- phase C (whole plane): SLOT_EPOCH re-roll -------------
        @pl.when((t + 1) % SLOT_EPOCH == 0)
        def _reslot():
            cur = st_out[:]
            idsv = cur[:, 0:k]
            pwv = cur[:, k:a]
            tsv = (pwv >> 12) - 1
            next_ep = ((t + 1) // SLOT_EPOCH).astype(jnp.uint32)
            tgt = _slot_of(seed, next_ep, idsv, k)
            key = jnp.where(idsv >= 0, _pack_key(idsv, tsv),
                            jnp.uint32(0))

            # contention resolved by a pairwise lex-max reduction TREE
            # over the K source slots (lex-max is associative and
            # commutative).  A sequential K-step chain compiles the
            # same bits, but XLA:CPU's interpret-mode compile blows up
            # superlinearly on the K-long dependent chain (measured:
            # k=16 ~10 s, k=24 >500 s); the tree is log-depth with
            # O(log K) live (N, K) planes.
            def cand(j):
                match = tgt[:, j:j + 1] == kk_n
                return (jnp.where(match, key[:, j:j + 1], jnp.uint32(0)),
                        jnp.where(match, pwv[:, j:j + 1], 0))

            def reduce_slots(lo, hi):
                if hi - lo == 1:
                    return cand(lo)
                mid = (lo + hi) // 2
                ka, pa = reduce_slots(lo, mid)
                kb, pb = reduce_slots(mid, hi)
                return _lex(ka, pa, kb, pb)

            kf, pf = reduce_slots(0, k)
            ids_r = jnp.where(kf > 0,
                              (kf & jnp.uint32(ID_MASK)).astype(i32), -1)
            pw_r = jnp.where(kf > 0, pf, 0)
            st_out[:] = jnp.concatenate([ids_r, pw_r, cur[:, a:]], axis=1)

        return ()

    jax.lax.fori_loop(0, s_ticks, tick, (), unroll=False)


@functools.partial(
    jax.jit, static_argnames=("n", "k", "f_rounds", "s_ticks", "t_remove",
                              "churn_lo", "churn_span", "can_rejoin",
                              "powerlaw", "interpret"))
def mega_overlay_ticks(st, sp, *, n: int, k: int, f_rounds: int,
                       s_ticks: int, t_remove: int, churn_lo: int,
                       churn_span: int, can_rejoin: bool, powerlaw: bool,
                       interpret: bool | None = None):
    """Run ``s_ticks`` whole overlay ticks in one Pallas launch.

    Args:
      st: i32[N, 2K+16] state plane (see module docstring lane map).
      sp: i32[_SP_NSCALARS + s_ticks*F] scalars + per-tick XOR masks.

    Returns ``(st', metrics i32[s_ticks, 128])`` — metric columns per
    the MET_* constants; lanes >= 8 are zero.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    w = st.shape[1]
    assert w == 2 * k + AUX_LANES and w <= 128, (w, k)
    assert st.shape[0] == n and n & (n - 1) == 0 and n >= 8
    assert f_rounds <= 8
    from ...state import NEVER
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec((n, w), lambda i, sp: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((n, w), lambda i, sp: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_ticks, 128), lambda i, sp: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((n, w), jnp.int32)
                        for _ in range(f_rounds)],
    )
    st2, met = pl.pallas_call(
        functools.partial(_kernel, n, k, f_rounds, s_ticks, t_remove,
                          churn_lo, churn_span, int(NEVER), can_rejoin,
                          powerlaw),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((n, w), jnp.int32),
                   jax.ShapeDtypeStruct((s_ticks, 128), jnp.int32)],
        # the whole-state-resident design needs more than the default
        # 16 MB scoped window; v5e has 128 MB of physical VMEM
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(sp, st)
    return st2, met
