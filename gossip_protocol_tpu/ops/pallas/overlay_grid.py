"""Grid-scale multi-tick Pallas megakernel: S overlay ticks per launch
with HBM-resident double-buffered state, for N above the VMEM envelope.

The whole-state-in-VMEM megakernel (overlay_mega.py) caps at
N <= MEGA_N_LIMIT; above it the per-tick fused kernel
(overlay_exchange.py) paid a fixed ~300-450 us Pallas launch plus an
~0.5-11.7 ms tail of per-tick XLA vector phases (docs/PERF.md) — at
N=65k/1M that floor was most of the tick, exactly the fixed cost the
reference's plain per-tick loop does not have
(/root/reference/Application.cpp:99-163).  This kernel removes it at
grid scale:

* **One packed state plane.**  ids and the packed (ts, hb) payload
  words share a single (N, 2K) i32 plane (2K <= 128 lanes = one native
  tile).  The per-peer aux state (own_hb, in_group, joinreq, joinrep,
  the F send flags — <= 24 bits total) rides the three spare HIGH
  bytes of pw lanes 0-2: pw words use only 24 bits ((ts+1)<<12 |
  hb+1), so the aux bytes are free HBM traffic.  Versus the two-plane
  per-tick kernel this halves plane traffic (docs/PERF.md item 1).
  Start/fail/rejoin/degree schedule columns are not stored at all:
  they are closed-form counter hashes recomputed in-kernel (the
  start-ramp comparisons are division-free:
  t > i*num//den  <=>  i*num < t*den).
* **Double-buffered HBM state.**  The state plane lives in ANY memory
  as a (2, N, 2K) OUTPUT buffer; grid step (s, i) manually DMAs its
  own row block plus the F XOR-partner blocks from phase s%2 and
  writes phase 1-s%2.  TPU grid execution is sequential and
  lexicographic, so every tick-s block is committed before any
  tick-(s+1) read — the cross-tick XOR-partner reads are well-defined
  (docs/PERF.md item 3).  Tick 0 reads a separate read-only init
  input (interpret mode does not propagate aliased writes back to
  reads, and the pure-output revolver is backend-agnostic; the init
  input also carries the boot row for the q scratch, see below).
* **Everything in-kernel** (docs/PERF.md item 2 — no per-tick XLA
  phases remain).  Each (s, i) step runs the complete tick for its
  rows: churn wipe (applied on load, to own and partner blocks alike),
  join/start decisions, F XOR exchange rounds (high mask bits pick the
  partner block, low bits are the in-VMEM group-roll butterfly), the
  lane-aligned lexicographic merges, JOINREP (the introducer's row
  snapshot revolves through scratch: the block that writes the
  introducer's row at tick s publishes tick s+1's broadcast), JOINREQ
  (tick s+1's per-slot aggregate is accumulated across tick-s blocks
  in scratch — a cross-block reduction made free by sequential grid
  order), winner extraction, TREMOVE staleness detection, the
  SLOT_EPOCH re-slot pass, drop-masked dissemination, and the
  per-tick metric rows.
* **Same bits.**  All randomness is the same counter-hash streams
  (utils/hash32.mix32); per-launch XOR masks ride scalar prefetch.
  Bit-identical to the XLA tick (tests/test_overlay_grid.py).

Scope: single device, power-of-two N with 2K <= 128, N a multiple of
the (power-of-two) row-block size, INTRODUCER in block 0, runs capped
at 4094 ticks, and step_num*(N-1) < 2^31 (the division-free ramp
comparisons must not overflow i32).

**Fleet batching** (models/overlay_grid.make_grid_fleet_run): the grid
carries a leading batch dimension — ``grid = (B, s_ticks, row
blocks)`` — so ONE launch steps B independent simulations (distinct
seeds, same config shape).  Each lane owns its slice of the
double-buffered plane, its scalar-prefetch row (seeds differ, so the
per-tick XOR masks differ per lane), and its metrics block; the
revolving scratch banks are reused across lanes, which is safe because
grid execution is sequential and every lane drains its deferred stores
at its own final step.  This is the batch-native alternative to
``jax.vmap``-of-``pallas_call`` (which would destroy the manual DMA
structure) and amortizes the per-launch dispatch floor B ways.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import tpu_compiler_params

from .overlay_mega import (MET_ADDS, MET_FALSE_REMOVALS,  # noqa: F401
                           MET_IN_GROUP, MET_RECV, MET_REMOVALS, MET_SENT,
                           MET_VICTIM, MET_VIEW, _lex, _sum_all, _umax0)

#: protocol ticks per launch (launch-floor amortization factor)
GRID_TICKS = 16

#: stored plane width: Mosaic requires DMA slices to be lane-aligned
#: to the (1, 128) tiling, so the (N, 2K) data plane is padded to a
#: full native tile width (zero extra HBM at K=64 — the tile padding
#: exists either way)
PLANE_W = 128

#: default row-block height (static; harness may override)
GRID_BLOCK_ROWS = 512

#: scalar-prefetch layout (deg thresholds + per-tick masks follow)
_GSP_T0 = 0
_GSP_SEED = 1
_GSP_VLO = 2
_GSP_VHI = 3
_GSP_FTICK = 4
_GSP_RAFTER = 5
_GSP_CTHR = 6
_GSP_CAFTER = 7
_GSP_DROP_ON = 8
_GSP_DROP_OPEN = 9
_GSP_DROP_CLOSE = 10
_GSP_DROP_THR = 11
_GSP_FAIL0 = 12
_GSP_REJOIN0 = 13
_GSP_STEP_NUM = 14
_GSP_STEP_DEN = 15
_GSP_NSCALARS = 16

_SIGN_I32 = np.int32(-2147483648)

#: aux bits ride the high bytes of pw lanes 0-2 (pw words are 24-bit):
#: lane 0 byte: own_hb bits [0, 8); lane 1 byte: own_hb bits [8, 12) |
#: in_group << 4 | joinreq << 5 | joinrep << 6; lane 2 byte: the
#: per-round send-flag bits (F <= 8)
_PW_MASK = 0x00FFFFFF


def _umax_i32(a, b):
    """Elementwise uint32 max on i32 bit patterns (sign-flip compare)."""
    return jnp.where((a ^ _SIGN_I32) > (b ^ _SIGN_I32), a, b)


def _xor_group_roll(x, sh: int):
    """x[r ^ sh] for power-of-two ``sh``: a roll-by-sh within each
    2sh-row group — one reshape+concat (overlay_mega.py phase A2)."""
    b, w = x.shape
    z = x.reshape(b // (2 * sh), 2 * sh, w)
    return jnp.concatenate([z[:, sh:], z[:, :sh]], axis=1).reshape(b, w)


def pack_aux_lanes(pw, own_hb, in_group, joinreq, joinrep, sf_bits):
    """Attach the aux bytes to pw lanes 0-2 (all i32; (rows, 1) aux).

    Shared by the kernel and the host harness so the plane layout has
    exactly one definition."""
    a0 = own_hb & 0xFF
    a1 = ((own_hb >> 8) & 0xF) | (in_group << 4) | (joinreq << 5) \
        | (joinrep << 6)
    return jnp.concatenate(
        [pw[:, 0:1] | (a0 << 24), pw[:, 1:2] | (a1 << 24),
         pw[:, 2:3] | (sf_bits << 24), pw[:, 3:]], axis=1)


def unpack_aux_lanes(pwr):
    """(pw_clean, own_hb, a1, sf_bits) from raw pw lanes (inverse of
    :func:`pack_aux_lanes`; a1 carries the three flag bits)."""
    a0 = (pwr[:, 0:1] >> 24) & 0xFF
    a1 = (pwr[:, 1:2] >> 24) & 0xFF
    sf = (pwr[:, 2:3] >> 24) & 0xFF
    return pwr & _PW_MASK, a0 | ((a1 & 0xF) << 8), a1, sf


def _kernel(n: int, k: int, f_rounds: int, s_ticks: int, b: int,
            t_remove: int, churn_lo: int,
            churn_span: int, never: int, can_rejoin: bool,
            churn_mode: bool, powerlaw: bool,
            ramp_live: bool, churn_live: bool, join_live: bool,
            drop_live: bool,
            sp_ref, init_in, plane_out, met_out, *refs):
    from ...config import INTRODUCER
    from ...models.overlay import (ID_BITS, ID_MASK, SLOT_EPOCH,
                                   _SALT_CHURN, _SALT_CHURN_TICK,
                                   _SALT_DEGREE, _SALT_GOSSIP_DROP,
                                   _SALT_JOINREP_DROP, _SALT_JOINREQ_DROP,
                                   _pack_key, _pack_th, _slot_of)
    from ...utils.hash32 import mix32

    own_bank = refs[0]                  # (2, B, W) double-banked
    part_banks = refs[1:1 + f_rounds]   # (2, B, W) each
    (bc_cur, bc_nxt, q_cur, q_nxt, acc_k, acc_p, ld_sems, st_sems) = \
        refs[1 + f_rounds:]

    i32 = jnp.int32
    w = 2 * k                # data lanes; the plane is padded to PLANE_W
    #                          (Mosaic DMA slices must be 128-aligned
    #                          along lanes)
    lane = pl.program_id(0)  # fleet lane (batch=1: always 0)
    s = pl.program_id(1)
    i = pl.program_id(2)
    t = sp_ref[lane, _GSP_T0] + s
    tu = t.astype(jnp.uint32)
    phase = jax.lax.rem(s, 2)
    seed = sp_ref[lane, _GSP_SEED].astype(jnp.uint32)
    churn_thr = sp_ref[lane, _GSP_CTHR].astype(jnp.uint32)
    drop_thr = sp_ref[lane, _GSP_DROP_THR].astype(jnp.uint32)
    ns = _GSP_NSCALARS + max(f_rounds - 1, 0)      # masks offset
    masks = [sp_ref[lane, ns + s * f_rounds + fi]
             for fi in range(f_rounds)]

    # ---- DMA in: banked prefetch ------------------------------------
    # Loads for step e = s*nb + i are issued one step AHEAD into bank
    # e%2 (hiding the HBM DMA latency behind step e-1's compute), except
    # at tick boundaries: a tick's first step must not read phase
    # 1-s%2 rows before the previous tick's deferred stores drain, so
    # it drains both store semaphores and issues its own loads inline.
    # Waits use size-matched descriptors (both sources transfer
    # identical byte counts).
    nb = n // b
    e_par = jax.lax.rem(s * nb + i, 2)             # this step's bank

    def issue_loads(s_e, i_e, bank):
        """Start the (1+F) block loads of step (s_e, i_e) into bank."""
        masks_e = [sp_ref[lane, ns + s_e * f_rounds + fi]
                   for fi in range(f_rounds)]
        phase_e = jax.lax.rem(s_e, 2)
        rows_e = [i_e * b] + [(i_e ^ (masks_e[fi] // b)) * b
                              for fi in range(f_rounds)]
        dsts = [own_bank.at[bank]] + [part_banks[fi].at[bank]
                                      for fi in range(f_rounds)]
        for j, (row0, dst) in enumerate(zip(rows_e, dsts)):
            @pl.when(s_e == 0)
            def _(row0=row0, dst=dst, j=j):
                pltpu.make_async_copy(init_in.at[lane, pl.ds(row0, b), :],
                                      dst, ld_sems.at[bank, j]).start()

            @pl.when(s_e > 0)
            def _(row0=row0, dst=dst, j=j):
                pltpu.make_async_copy(
                    plane_out.at[lane, phase_e, pl.ds(row0, b), :],
                    dst, ld_sems.at[bank, j]).start()

    def wait_loads(bank):
        for j in range(1 + f_rounds):
            dst = own_bank.at[bank] if j == 0 \
                else part_banks[j - 1].at[bank]
            pltpu.make_async_copy(init_in.at[0, pl.ds(0, b), :], dst,
                                  ld_sems.at[bank, j]).wait()

    def wait_store(bank):
        pltpu.make_async_copy(
            own_bank.at[bank],
            plane_out.at[0, 0, pl.ds(0, b), :], st_sems.at[bank]).wait()

    @pl.when((i == 0) & (s > 0))
    def _():
        # tick boundary: drain the previous tick's deferred stores
        # (both banks when its tail held two in flight)
        wait_store(1 - e_par)
        if nb > 1:
            wait_store(e_par)

    @pl.when(i == 0)
    def _():
        issue_loads(s, i, e_par)           # not prefetched (boundary)
    wait_loads(e_par)

    @pl.when((i + 1 < nb) & (i > 0))
    def _():
        # the store issued last step used bank 1-e_par's scratch;
        # drain it before the prefetch overwrites that bank
        wait_store(1 - e_par)

    @pl.when(i + 1 < nb)
    def _():
        issue_loads(s, i + 1, 1 - e_par)

    # all compute below operates on this step's bank
    own_scr = own_bank.at[e_par]
    part_scrs = [part_banks[fi].at[e_par] for fi in range(f_rounds)]

    # ---- tick-boundary revolves (first block of each tick) ---------
    # the join scratch (broadcast row + JOINREQ aggregate) only
    # revolves while join machinery is live this launch
    if join_live:
        @pl.when((i == 0) & (s == 0))
        def _():
            # boot rows [N, N+8): row N the introducer broadcast row,
            # row N+1 the JOINREQ aggregate (ANY-space input, so DMA
            # through the bc scratch; the store semaphore is idle here)
            cp = pltpu.make_async_copy(init_in.at[lane, pl.ds(n, 8), :],
                                       bc_cur, st_sems.at[0])
            cp.start()
            cp.wait()
            q_cur[0:1, :] = bc_cur[1:2, 0:k]

        @pl.when((i == 0) & (s > 0))
        def _():
            bc_cur[0:1, :] = bc_nxt[0:1, :]
            q_cur[0:1, :] = q_nxt[0:1, :]

        @pl.when(i == 0)
        def _():
            q_nxt[0:1, :] = jnp.zeros((1, k), i32)

    @pl.when(i == 0)
    def _():
        met_out[0, pl.ds(s, 1), :] = jnp.zeros((1, 128), i32)

    # ---- introducer gates + schedule helpers -----------------------
    # ``wipe``: a rejoin can fire at a tick of THIS launch (static);
    # churn_live=False guarantees failed/rejoining are identically
    # False for every row, the introducer included
    wipe = can_rejoin and churn_live
    fail0 = sp_ref[lane, _GSP_FAIL0]
    rejoin0 = sp_ref[lane, _GSP_REJOIN0]
    if churn_live:
        failed0 = (t > fail0) & (t <= rejoin0)
        proc0 = (t > 0) & jnp.logical_not(failed0)
    else:
        proc0 = t > 0
    slot_ep = (t // SLOT_EPOCH).astype(jnp.uint32)

    def sched_of(subj):
        """(fail, rejoin) of subject ids — closed form, any shape.
        ``churn_mode`` is static (cfg.churn_rate > 0), so fail-mode
        configs never pay the two per-entry churn hashes."""
        if churn_mode:
            subj_u = subj.astype(jnp.uint32)
            churned = (mix32(seed, subj_u, np.uint32(_SALT_CHURN))
                       < churn_thr) & (subj != INTRODUCER)
            churn_fail = churn_lo + (
                mix32(seed, subj_u, np.uint32(_SALT_CHURN_TICK))
                % np.uint32(churn_span)).astype(i32)
            fail = jnp.where(churned, churn_fail, never)
            after = sp_ref[lane, _GSP_CAFTER]
        else:
            fail = jnp.where(
                (subj >= sp_ref[lane, _GSP_VLO])
                & (subj < sp_ref[lane, _GSP_VHI]),
                sp_ref[lane, _GSP_FTICK], never)
            after = sp_ref[lane, _GSP_RAFTER]
        rejoin = jnp.where((fail != never) & (after != never),
                           fail + after, never)
        return fail, rejoin

    # ---- own rows: unpack + wipe + decisions -----------------------
    rows = i * b + jax.lax.broadcasted_iota(i32, (b, 1), 0)
    rows_u = rows.astype(jnp.uint32)
    kk = jax.lax.broadcasted_iota(i32, (b, k), 1)
    fis = jax.lax.broadcasted_iota(i32, (b, f_rounds), 1)
    is_intro = rows == INTRODUCER

    raw = own_scr[:]
    ids0 = raw[:, 0:k]
    pw0, own_hb0, a1, _ = unpack_aux_lanes(raw[:, k:w])
    in_group0 = (a1 & 0x10) > 0
    if join_live:
        joinreq0 = (a1 & 0x20) > 0
        joinrep0 = (a1 & 0x40) > 0

    # ``proc`` as an optional: None means "statically all-processing"
    # (ramp over, nobody failed) — downstream gates vanish instead of
    # AND-ing an all-true vector through the hot loop
    if churn_live:
        fail, rejoin = sched_of(rows)
        failed = (t > fail) & (t <= rejoin)
    if ramp_live:
        # division-free start ramp (see module docstring); num/den
        # ride the sp vector so the runtime sched argument is honored
        # like every other schedule field
        step_num = sp_ref[lane, _GSP_STEP_NUM]
        step_den = sp_ref[lane, _GSP_STEP_DEN]
        ramp = rows * step_num
        t_gt_start = ramp < t * step_den
        at_start = (ramp >= t * step_den) & (ramp < (t + 1) * step_den)
        proc = t_gt_start & ~failed if churn_live else t_gt_start
    else:
        proc = jnp.logical_not(failed) if churn_live else None
    if wipe:                                  # churn wipe (own rows)
        rejoining = t == rejoin
        ids0 = jnp.where(rejoining, -1, ids0)
        pw0 = jnp.where(rejoining, 0, pw0)
        in_group0 = in_group0 & ~rejoining
        own_hb0 = jnp.where(rejoining, 0, own_hb0)

    # ``starting`` as an optional: None means "no start/rejoin event
    # can fire this launch" (join_live=False implies None — planner
    # invariant)
    if ramp_live and wipe:
        starting = at_start | rejoining
    elif ramp_live:
        starting = at_start
    elif wipe:
        starting = rejoining
    else:
        starting = None

    in_group = in_group0
    if join_live:
        jrep = joinrep0 & proc if proc is not None else joinrep0
        in_group = in_group | jrep
    if starting is not None:
        in_group = in_group | (starting & is_intro)
    ops = proc & in_group if proc is not None else in_group
    own_hb = own_hb0 + ops.astype(i32)

    # ---- merge accumulator init ------------------------------------
    # the key's ts+1 field IS the pw word's high field: no unpack
    kmax = jnp.where(ids0 >= 0,
                     ((pw0 >> 12).astype(jnp.uint32) << ID_BITS)
                     | ids0.astype(jnp.uint32),
                     jnp.uint32(0))
    pacc = pw0
    recv = jnp.zeros((b, 1), i32)
    # freshness gate on the packed word: t - ts < t_remove  <=>
    # ts + 1 >= t - t_remove + 2  <=>  pw >= (t - t_remove + 2) << 12
    # (the hb+1 bits below bit 12 are in [1, 4095], so they cannot
    # carry a ts+1 = t-t_remove+1 word across the floor)
    fresh_floor = (t - t_remove + 2) << 12
    # direct entries: scalar-precomputed key/payload high fields
    key_t1 = t.astype(jnp.uint32) << ID_BITS          # ts = t - 1
    pw_t1 = t << 12                                   # _pack_th(t-1, .)

    # ---- F exchange rounds -----------------------------------------
    lgb = b.bit_length() - 1
    for fi in range(f_rounds):
        m = masks[fi]
        for j in range(lgb):                 # in-block butterfly
            sh = 1 << j

            @pl.when(((m >> j) & 1) == 1)
            def _(fi=fi, sh=sh):
                part_scrs[fi][:] = _xor_group_roll(part_scrs[fi][:], sh)

        wv = part_scrs[fi][:]
        in_ids = wv[:, 0:k]
        in_p, own_p, _, pa2 = unpack_aux_lanes(wv[:, k:w])
        partner = rows ^ m
        if wipe:                             # wipe-on-load (partner)
            _, prejoin = sched_of(partner)
            prj = t == prejoin
            in_ids = jnp.where(prj, -1, in_ids)
            in_p = jnp.where(prj, 0, in_p)
            own_p = jnp.where(prj, 0, own_p)
        flag = ((pa2 >> fi) & 1) > 0
        ok = flag & proc if proc is not None else flag
        valid = ok & (in_ids >= 0) & (in_p >= fresh_floor) \
            & (in_ids != rows)
        key = jnp.where(valid,
                        ((in_p >> 12).astype(jnp.uint32) << ID_BITS)
                        | in_ids.astype(jnp.uint32),
                        jnp.uint32(0))
        kmax, pacc = _lex(kmax, pacc, key, jnp.where(valid, in_p, 0))
        if t_remove > 1:                     # partner self-entry (age 1)
            psl = _slot_of(seed, slot_ep, partner, k)
            pkey = jnp.where(ok, key_t1 | partner.astype(jnp.uint32),
                             jnp.uint32(0))
            pp = jnp.where(ok, pw_t1 | (own_p + 1), 0)
            match = psl == kk
            kmax, pacc = _lex(kmax, pacc,
                              jnp.where(match, pkey, jnp.uint32(0)),
                              jnp.where(match, pp, 0))
        recv = recv + ok.astype(i32)

    # ---- JOINREP + JOINREQ merges (scratch-staged + predicated) ----
    # Both are rare per block — JOINREPs only reach joining/rejoining
    # rows and the JOINREQ aggregate only lands in the introducer's
    # block — so the accumulator revolves through scratch and the ~30
    # vector ops run under pl.when instead of burning every step.
    # With join machinery statically dead this launch, the whole block
    # (and the accumulator's scratch round-trip) disappears.
    if join_live:
        jrep_any = _sum_all(jrep)[0, 0] > 0
        acc_k[:] = kmax.astype(i32)
        acc_p[:] = pacc

        @pl.when(jrep_any)
        def _():
            kmax = acc_k[:].astype(jnp.uint32)
            pacc = acc_p[:]
            bcrow = bc_cur[0:1, :]
            bc_ids = bcrow[:, 0:k]
            bc_pw, bc_hb, _, _ = unpack_aux_lanes(bcrow[:, k:w])
            if wipe:                         # wipe-on-load (introducer)
                rejoining0 = t == rejoin0
                bc_ids = jnp.where(rejoining0, -1, bc_ids)
                bc_pw = jnp.where(rejoining0, 0, bc_pw)
                bc_hb = jnp.where(rejoining0, 0, bc_hb)
            j_valid = jrep & (bc_ids >= 0) & (bc_pw >= fresh_floor) \
                & (bc_ids != rows)
            jkey = jnp.where(j_valid,
                             ((bc_pw >> 12).astype(jnp.uint32) << ID_BITS)
                             | bc_ids.astype(jnp.uint32),
                             jnp.uint32(0))
            kmax, pacc = _lex(kmax, pacc, jkey,
                              jnp.where(j_valid, bc_pw, 0))
            if t_remove > 1:                 # the introducer's self-entry
                intro_vec = jnp.zeros_like(rows) + INTRODUCER
                islot = _slot_of(seed, slot_ep, intro_vec, k)
                iok = jrep & ~is_intro
                ikey = jnp.where(iok, key_t1 | jnp.uint32(INTRODUCER),
                                 jnp.uint32(0))
                ip = jnp.where(iok, pw_t1 | (bc_hb + 1), 0)
                imatch = islot == kk
                kmax, pacc = _lex(kmax, pacc,
                                  jnp.where(imatch, ikey, jnp.uint32(0)),
                                  jnp.where(imatch, ip, 0))
            acc_k[:] = kmax.astype(i32)
            acc_p[:] = pacc

        @pl.when(i == INTRODUCER // b)
        def _():
            kmax = acc_k[:].astype(jnp.uint32)
            pacc = acc_p[:]
            q_kf = q_cur[0:1, :].astype(jnp.uint32)
            q_pf = jnp.where(q_kf > 0, _pack_th(t, 1), 0)
            kmax, pacc = _lex(kmax, pacc,
                              jnp.where(is_intro, q_kf, jnp.uint32(0)),
                              jnp.where(is_intro, q_pf, 0))
            acc_k[:] = kmax.astype(i32)
            acc_p[:] = pacc

        kmax = acc_k[:].astype(jnp.uint32)
        pacc = acc_p[:]
        jreq = joinreq0 & proc0

    # ---- winner extraction + staleness detection -------------------
    # the key IS (ts+1, id) and pacc IS the winner's packed pw word,
    # so occupancy and staleness are single uint compares on kmax:
    # occupied <=> kmax > 0; stale <=> ts <= t - t_remove <=>
    # kmax < (t - t_remove + 2) << ID_BITS
    occ1 = kmax > 0
    ids1 = jnp.where(occ1, (kmax & jnp.uint32(ID_MASK)).astype(i32), -1)
    # clamp before the uint cast: early in the run t - t_remove + 2 is
    # negative and would wrap to a huge ceiling (everything "stale")
    stale_ceil = (jnp.maximum(t - t_remove + 2, 0).astype(jnp.uint32)
                  << ID_BITS)
    stale = occ1 & (kmax < stale_ceil) & ops
    ids2 = jnp.where(stale, -1, ids1)
    pw2 = jnp.where(stale | ~occ1, 0, pacc)

    if churn_live:
        # subject fail/rejoin for the accuracy metrics
        subj = jnp.where(ids1 >= 0, ids1, 0)
        s_fail, s_rejoin = sched_of(subj)
        subj_failed = (t > s_fail) & (t <= s_rejoin)

    # ---- dissemination: next tick's flags --------------------------
    if drop_live:
        active = (sp_ref[lane, _GSP_DROP_ON] > 0) \
            & (t > sp_ref[lane, _GSP_DROP_OPEN]) \
            & (t <= sp_ref[lane, _GSP_DROP_CLOSE])
        gdrop = mix32(seed, tu, rows_u, fis.astype(jnp.uint32),
                      np.uint32(_SALT_GOSSIP_DROP)) < drop_thr
        sf_next = ops & ~(active & gdrop)
    else:
        sf_next = jnp.broadcast_to(ops, (b, f_rounds))
    if powerlaw:
        du = mix32(seed, rows_u, np.uint32(_SALT_DEGREE))
        thr_hits = jnp.zeros((b, 1), i32)
        for j in range(f_rounds - 1):
            thr_hits = thr_hits + (
                du < sp_ref[lane, _GSP_NSCALARS + j].astype(jnp.uint32)
            ).astype(i32)
        deg = 1 + thr_hits
        sf_next = sf_next & (fis < deg)
    if join_live:
        if starting is not None:
            joinreq_new = starting & ~is_intro
            if drop_live:
                qdrop = mix32(seed, tu, rows_u,
                              np.uint32(_SALT_JOINREQ_DROP)) < drop_thr
                joinreq_sent = joinreq_new & ~(active & qdrop)
            else:
                joinreq_sent = joinreq_new
        else:
            joinreq_sent = None              # statically no new joins
        if drop_live:
            pdrop = mix32(seed, tu, rows_u,
                          np.uint32(_SALT_JOINREP_DROP)) < drop_thr
            joinrep_sent = jreq & ~(active & pdrop)
        else:
            joinrep_sent = jreq
        # in-flight holds: live_hold is statically False once the ramp
        # is over and nobody is failed (proc is None)
        hold_q = joinreq0 & jnp.logical_not(proc0)
        if churn_live:
            hold_q = hold_q & jnp.logical_not(failed0)
        joinreq_next = hold_q if joinreq_sent is None \
            else joinreq_sent | hold_q
        if proc is None:
            joinrep_next = joinrep_sent
        else:
            live_hold = ~proc & ~failed if churn_live else ~proc
            joinrep_next = joinrep_sent | (joinrep0 & live_hold)

    # ---- metrics (pre-re-slot table, like the XLA path) ------------
    removals_cnt = _sum_all(stale)
    sent_cnt = _sum_all(sf_next)
    recv_cnt = _sum_all(recv)
    if join_live:
        if joinreq_sent is not None:
            sent_cnt = sent_cnt + _sum_all(joinreq_sent)
        sent_cnt = sent_cnt + _sum_all(joinrep_sent)
        recv_cnt = recv_cnt + _sum_all(jrep) + _sum_all(jreq)
    if churn_live:
        false_rem_cnt = _sum_all(stale & ~subj_failed)
        victim_cnt = _sum_all((ids2 >= 0) & subj_failed & ~stale)
    else:
        # no subject can be inside its fail window this launch
        false_rem_cnt = removals_cnt
        victim_cnt = jnp.zeros((1, 1), i32)
    delta = jnp.concatenate([
        _sum_all(in_group),
        _sum_all(ids2 >= 0),
        _sum_all((ids1 != ids0) & (ids1 >= 0)),
        removals_cnt,
        false_rem_cnt,
        victim_cnt,
        sent_cnt,
        recv_cnt,
    ], axis=1)
    met_out[0, pl.ds(s, 1), 0:8] = met_out[0, pl.ds(s, 1), 0:8] + delta

    # ---- tick s+1's JOINREQ aggregate (cross-block scratch) --------
    # the lookahead only matters for ticks whose successor is inside
    # this launch (the host recomputes the boot aggregate at every
    # launch boundary), so a join-dead launch skips it entirely
    t1 = t + 1
    slot_ep1 = (t1 // SLOT_EPOCH).astype(jnp.uint32)
    if join_live:
        if churn_live:
            failed0_1 = (t1 > fail0) & (t1 <= rejoin0)
            proc0_1 = (t1 > 0) & jnp.logical_not(failed0_1)
        else:
            proc0_1 = t1 > 0
        jq1 = joinreq_next & proc0_1 & ~is_intro
        qslot1 = _slot_of(seed, slot_ep1, rows, k)
        qkey1 = jnp.where(jq1, _pack_key(rows, jnp.zeros_like(rows) + t1),
                          jnp.uint32(0))
        cand = jnp.where(qslot1 == kk, qkey1, jnp.uint32(0))
        blkmax = _umax0(cand).astype(i32)          # (1, K) key bits
        q_nxt[0:1, :] = _umax_i32(q_nxt[0:1, :], blkmax)

    # ---- pack + stage the new block in scratch ---------------------
    pw_out = pack_aux_lanes(pw2, own_hb, in_group.astype(i32),
                            joinreq_next.astype(i32) if join_live else 0,
                            joinrep_next.astype(i32) if join_live else 0,
                            (sf_next.astype(i32)
                             << fis).sum(1, keepdims=True))
    pad = [jnp.zeros((b, PLANE_W - w), i32)] if w < PLANE_W else []
    own_scr[:] = jnp.concatenate([ids2, pw_out] + pad, axis=1)

    # ---- SLOT_EPOCH re-roll (own rows; ref-staged, predicated) -----
    @pl.when((t + 1) % SLOT_EPOCH == 0)
    def _reslot():
        cur = own_scr[:]
        idsv = cur[:, 0:k]
        pwv, r_hb, r_a1, r_sf = unpack_aux_lanes(cur[:, k:w])
        tsv = (pwv >> 12) - 1
        next_ep = slot_ep1
        tgt = _slot_of(seed, next_ep, idsv, k)
        key = jnp.where(idsv >= 0, _pack_key(idsv, tsv),
                        jnp.uint32(0))

        # pairwise max-reduction tree over the K source slots.  A
        # row's candidate keys are pairwise DISTINCT (one entry per
        # id, and the key embeds the id), so the payload lex-compare
        # of the generic merge is redundant: max on the key alone and
        # carry the payload by the same select
        def cand_slot(j):
            match = tgt[:, j:j + 1] == kk
            return (jnp.where(match, key[:, j:j + 1], jnp.uint32(0)),
                    jnp.where(match, pwv[:, j:j + 1], 0))

        def reduce_slots(lo, hi):
            if hi - lo == 1:
                return cand_slot(lo)
            mid = (lo + hi) // 2
            ka, pa = reduce_slots(lo, mid)
            kb, pb = reduce_slots(mid, hi)
            better = kb > ka
            return (jnp.where(better, kb, ka),
                    jnp.where(better, pb, pa))

        kf, pf = reduce_slots(0, k)
        ids_r = jnp.where(kf > 0,
                          (kf & jnp.uint32(ID_MASK)).astype(i32), -1)
        pw_r = jnp.where(kf > 0, pf, 0)
        own_scr[:] = jnp.concatenate(
            [ids_r, pack_aux_lanes(pw_r, r_hb, (r_a1 >> 4) & 1,
                                   (r_a1 >> 5) & 1, (r_a1 >> 6) & 1,
                                   r_sf)] + pad, axis=1)

    # ---- publish tick s+1's introducer broadcast row ---------------
    if join_live:
        @pl.when(i == INTRODUCER // b)
        def _():
            bc_nxt[0:1, :] = own_scr[INTRODUCER % b:INTRODUCER % b + 1, :]

    # ---- DMA out: commit the block to the next phase ---------------
    # deferred: the wait happens when this bank's scratch is next
    # reused (prefetch / tick-boundary drain), hiding the store
    # latency behind the following step's compute
    pltpu.make_async_copy(
        own_scr, plane_out.at[lane, 1 - phase, pl.ds(i * b, b), :],
        st_sems.at[e_par]).start()

    @pl.when((s == s_ticks - 1) & (i == nb - 1))
    def _():
        wait_store(e_par)                  # drain before kernel exit
        if nb > 1:
            wait_store(1 - e_par)


@functools.partial(
    jax.jit, static_argnames=("n", "k", "f_rounds", "s_ticks", "b",
                              "t_remove",
                              "churn_lo", "churn_span", "can_rejoin",
                              "churn_mode", "powerlaw", "ramp_live",
                              "churn_live", "join_live", "drop_live",
                              "batch", "interpret"))
def grid_overlay_ticks(init, sp, *, n: int, k: int, f_rounds: int,
                       s_ticks: int, b: int, t_remove: int,
                       churn_lo: int,
                       churn_span: int, can_rejoin: bool,
                       churn_mode: bool, powerlaw: bool,
                       ramp_live: bool = True, churn_live: bool = True,
                       join_live: bool = True, drop_live: bool = True,
                       batch: int = 1,
                       interpret: bool | None = None):
    """Run ``s_ticks`` whole overlay ticks in one grid-scale launch.

    The four ``*_live`` flags are static phase-elision switches (see
    models/segments.py for their exact OFF guarantees); with all four
    on the kernel is the unsegmented original, valid at any clock.

    Args:
      init: i32[N + 8, PLANE_W] — rows [0, N) the packed state plane
        (lanes [0, K) ids, [K, 2K) pw-with-aux-bytes, rest zero pad —
        see module docstring); row N the boot introducer broadcast row
        (the introducer's plane row at the launch's start tick,
        pre-wipe); row N+1 lanes [0, K) the boot JOINREQ aggregate
        (uint32 key bits as i32) for the start tick.
      sp: i32[NS + (F-1) + s_ticks*F] scalars, power-law degree
        thresholds, and the per-tick XOR masks.
      batch: fleet width B (module docstring).  With ``batch > 1`` the
        grid grows a leading lane dimension and every array gains a
        leading B axis: init i32[B, N+8, PLANE_W], sp i32[B, NS...],
        returns (plane2 i32[B, 2, N, PLANE_W], metrics
        i32[B, s_ticks, 128]).  One launch steps every lane.

    Returns ``(plane2 i32[2, N, 2K], metrics i32[s_ticks, 128])`` —
    the end state is ``plane2[s_ticks % 2]``; metric columns per the
    MET_* constants of overlay_mega.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    squeeze = init.ndim == 2
    if squeeze:
        assert batch == 1, (batch, init.shape)
        init = init[None]
        sp = sp[None]
    assert init.shape == (batch, n + 8, PLANE_W) and 2 * k <= PLANE_W, \
        (init.shape, batch, k)
    assert sp.shape[0] == batch, (sp.shape, batch)
    assert n % b == 0 and b & (b - 1) == 0 and 8 <= b, (n, b)
    assert f_rounds <= 8
    # the kernel's join_live=False form assumes no start/rejoin event
    # can fire this launch (models/segments.py planner invariant)
    assert join_live or not (ramp_live or (can_rejoin and churn_live))
    from ...config import INTRODUCER
    from ...state import NEVER
    assert INTRODUCER < b, "introducer must live in row block 0"
    nb = n // b
    i32 = jnp.int32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, s_ticks, nb),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, s_ticks, 128), lambda l, s, i, sp: (l, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((2, b, PLANE_W), i32)
                        for _ in range(1 + f_rounds)]
        + [pltpu.VMEM((8, PLANE_W), i32), pltpu.VMEM((8, PLANE_W), i32),
           pltpu.VMEM((8, k), i32), pltpu.VMEM((8, k), i32),
           pltpu.VMEM((b, k), i32), pltpu.VMEM((b, k), i32),
           pltpu.SemaphoreType.DMA((2, f_rounds + 1)),
           pltpu.SemaphoreType.DMA((2,))],
    )
    plane2, met = pl.pallas_call(
        functools.partial(_kernel, n, k, f_rounds, s_ticks, b, t_remove,
                          churn_lo, churn_span,
                          int(NEVER), can_rejoin, churn_mode, powerlaw,
                          ramp_live, churn_live, join_live, drop_live),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((batch, 2, n, PLANE_W), i32),
                   jax.ShapeDtypeStruct((batch, s_ticks, 128), i32)],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(sp, init)
    if squeeze:
        return plane2[0], met[0]
    return plane2, met
