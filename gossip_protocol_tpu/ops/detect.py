"""Failure detection as a vectorized staleness mask.

Replaces the reference's per-node reverse scan over the member list
(``nodeLoopOps``, MP1Node.cpp:339-348): every entry whose timestamp is
``TREMOVE`` or more ticks old is removed and logged.  There is no
suspect/TFAIL phase in the reference (``pingCounter``/``timeOutCounter``
are initialized, MP1Node.cpp:108-109, but never read), so staleness goes
straight to removal here too.
"""

from __future__ import annotations

import jax.numpy as jnp


def staleness_mask(ops_mask, known, ts, now, t_remove):
    """bool[N, N]: entries to remove this tick.

    Args:
      ops_mask: bool[N] — peers running their periodic ops this tick
        (started, live, in-group; Application.cpp:153, MP1Node.cpp:185-190).
      known:    bool[N, N] — current membership tables.
      ts:       i32[N, N] — entry timestamps.
      now:      i32 scalar — current logical time.
      t_remove: TREMOVE horizon (MP1Node.h:21).

    The comparison is ``now - ts >= t_remove`` exactly as in
    MP1Node.cpp:340.
    """
    return ops_mask[:, None] & known & (now - ts >= t_remove)
