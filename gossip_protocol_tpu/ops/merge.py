"""Gossip merge reductions.

The reference merges incoming member lists one message at a time with
linear scans (``recvCallBack`` GOSSIP branch, MP1Node.cpp:234-257:
per-entry ``check_exist`` O(N) lookup + max-compare).  On TPU the whole
receive+merge phase for *all* peers collapses into one masked max
reduction over the sender axis — a (max, select) semiring "matmul":

    M[r, j] = max over s of  hb[s, j]   where  recv_from[r, s] and known[s, j]

Four reductions share the same pass (all-sources max, fresh-sources max,
fresh-sources timestamp max, fresh-source existence); they are computed
blockwise over the sender axis with ``lax.scan`` so peak memory stays
O(R * B * J) instead of O(R * S * J).  A Pallas kernel with the same
contract lives in ``ops/pallas/maxmerge.py`` for the hot path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

#: Fill value for "no contributing sender".  Real heartbeats are >= 1
#: (entries are created with heartbeat 1, MP1Node.cpp:270) and real
#: timestamps are >= 0, so -1 is unambiguous.
FILL = jnp.int32(-1)


@partial(jax.jit, static_argnames=("t_remove", "block_size"))
def gossip_reductions(recv_from, known, hb, ts, now, *,
                      t_remove: int, block_size: int = 128):
    """Batched piggyback-merge statistics for every receiver at once.

    Args:
      recv_from: bool[R, S] — receiver r consumed a GOSSIP from sender s
        this tick.
      known:     bool[S, J] — sender s's member list contains j (the
        payload membership, frozen at send time).
      hb:        i32[S, J] — sender s's recorded heartbeat for j.
      ts:        i32[S, J] — sender s's recorded timestamp for j.
      now:       i32 scalar — current logical time (receive time).
      t_remove:  the TREMOVE staleness horizon (MP1Node.h:21); an entry
        is *fresh* iff ``now - ts < t_remove`` (the add gate,
        MP1Node.cpp:294).
      block_size: sender-axis block width for the scan.

    Returns:
      (m_hb_all, m_hb_fresh, m_ts_fresh, any_fresh), each [R, J]:
        m_hb_all   — max heartbeat over all contributing senders (FILL
                     if none).  Drives the merge-into-existing rule
                     "adopt if strictly greater" (MP1Node.cpp:248-251).
        m_hb_fresh — max heartbeat over *fresh* contributions only.
        m_ts_fresh — max sender timestamp over fresh contributions.
        any_fresh  — bool: some fresh contribution exists (the add gate).
    """
    r_dim, s_dim = recv_from.shape
    j_dim = known.shape[1]
    b = min(block_size, s_dim)
    nb = -(-s_dim // b)
    pad = nb * b - s_dim

    if pad:
        recv_from = jnp.pad(recv_from, ((0, 0), (0, pad)))
        known = jnp.pad(known, ((0, pad), (0, 0)))
        hb = jnp.pad(hb, ((0, pad), (0, 0)))
        ts = jnp.pad(ts, ((0, pad), (0, 0)))

    recv_blocks = recv_from.reshape(r_dim, nb, b).transpose(1, 0, 2)  # [nb, R, B]
    known_blocks = known.reshape(nb, b, j_dim)
    hb_blocks = hb.reshape(nb, b, j_dim)
    ts_blocks = ts.reshape(nb, b, j_dim)

    # Derive the accumulator initializers from the inputs (instead of
    # plain constants) so that under shard_map they carry the same
    # varying-axis type as the per-block contributions — a constant
    # init would make the scan carry type-mismatch on a sharded mesh.
    zero = recv_from[:, :1].astype(jnp.int32) * (hb[:1, :] * 0)
    init = (zero + FILL, zero + FILL, zero + FILL, zero.astype(bool))

    def body(carry, blk):
        m_all, m_fr, t_fr, anyf = carry
        d, kn, h, tsb = blk
        contrib = d[:, :, None] & kn[None]                    # [R, B, J]
        m_all = jnp.maximum(m_all, jnp.where(contrib, h[None], FILL).max(1))
        fresh = contrib & (now - tsb[None] < t_remove)
        m_fr = jnp.maximum(m_fr, jnp.where(fresh, h[None], FILL).max(1))
        t_fr = jnp.maximum(t_fr, jnp.where(fresh, tsb[None], FILL).max(1))
        anyf = anyf | fresh.any(1)
        return (m_all, m_fr, t_fr, anyf), None

    (m_all, m_fr, t_fr, anyf), _ = lax.scan(
        body, init, (recv_blocks, known_blocks, hb_blocks, ts_blocks))
    return m_all, m_fr, t_fr, anyf
