"""Gossip merge reductions.

The reference merges incoming member lists one message at a time with
linear scans (``recvCallBack`` GOSSIP branch, MP1Node.cpp:234-257:
per-entry ``check_exist`` O(N) lookup + max-compare).  On TPU the whole
receive+merge phase for *all* peers collapses into one masked max
reduction over the sender axis — a (max, select) semiring "matmul":

    M[r, j] = max over s of  hb[s, j]   where  recv_from[r, s] and known[s, j]

The mask-select is expressed as a *product*: with payloads shifted up by
one (``A1 = known ? hb+1 : 0``) and the delivery mask as int 0/1, the
masked select is ``d * A1`` (one VPU multiply instead of a
select/where), the reduction is a plain max, and the no-contribution
case falls out as 0 → FILL after shifting back down.  Three such
product-max reductions share one blockwise pass over the sender axis
(``lax.scan``), so peak memory stays O(R * B * J) instead of
O(R * S * J).  :func:`gossip_reductions_mxu` computes the same
contract by MXU level decomposition and is the TPU hot path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

#: Fill value for "no contributing sender".  Real heartbeats are >= 1
#: (entries are created with heartbeat 1, MP1Node.cpp:270) and real
#: timestamps are >= 0, so -1 is unambiguous.
FILL = jnp.int32(-1)


def merge_payloads(known, hb, ts, now, t_remove):
    """Shift-encoded payload planes for the product-max reductions.

    Returns int32 [S, J] planes:
      a1 — ``known ? hb + 1 : 0``            (all contributions)
      f1 — ``fresh ? hb + 1 : 0``            (fresh contributions)
      t1 — ``fresh ? ts + 1 : 0``            (fresh timestamps)
    where *fresh* is the receive-time add gate ``now - ts < t_remove``
    (MP1Node.cpp:294).  Heartbeats/timestamps are bounded by the run
    length (<= MAX_TIME 3600, EmulNet.h:11), so the +1 shift never
    overflows and 0 unambiguously encodes "nothing".
    """
    k = known.astype(jnp.int32)
    fresh = k * (now - ts < t_remove)
    return k * (hb + 1), fresh * (hb + 1), fresh * (ts + 1)


@partial(jax.jit, static_argnames=("t_remove", "block_size"))
def gossip_reductions(recv_from, known, hb, ts, now, *,
                      t_remove: int, block_size: int = 128):
    """Batched piggyback-merge statistics for every receiver at once.

    Args:
      recv_from: bool[R, S] — receiver r consumed a GOSSIP from sender s
        this tick.
      known:     bool[S, J] — sender s's member list contains j (the
        payload membership, frozen at send time).
      hb:        i32[S, J] — sender s's recorded heartbeat for j.
      ts:        i32[S, J] — sender s's recorded timestamp for j.
      now:       i32 scalar — current logical time (receive time).
      t_remove:  the TREMOVE staleness horizon (MP1Node.h:21); an entry
        is *fresh* iff ``now - ts < t_remove`` (the add gate,
        MP1Node.cpp:294).
      block_size: sender-axis block width for the scan.

    Returns:
      (m_hb_all, m_hb_fresh, m_ts_fresh, any_fresh), each [R, J]:
        m_hb_all   — max heartbeat over all contributing senders (FILL
                     if none).  Drives the merge-into-existing rule
                     "adopt if strictly greater" (MP1Node.cpp:248-251).
        m_hb_fresh — max heartbeat over *fresh* contributions only.
        m_ts_fresh — max sender timestamp over fresh contributions.
        any_fresh  — bool: some fresh contribution exists (the add gate).
    """
    r_dim, s_dim = recv_from.shape
    j_dim = known.shape[1]
    b = min(block_size, s_dim)
    nb = -(-s_dim // b)
    pad = nb * b - s_dim

    a1, f1, t1 = merge_payloads(known, hb, ts, now, t_remove)
    d = recv_from.astype(jnp.int32)
    if pad:
        d = jnp.pad(d, ((0, 0), (0, pad)))
        a1 = jnp.pad(a1, ((0, pad), (0, 0)))
        f1 = jnp.pad(f1, ((0, pad), (0, 0)))
        t1 = jnp.pad(t1, ((0, pad), (0, 0)))

    d_blocks = d.reshape(r_dim, nb, b).transpose(1, 0, 2)   # [nb, R, B]
    a1_blocks = a1.reshape(nb, b, j_dim)
    f1_blocks = f1.reshape(nb, b, j_dim)
    t1_blocks = t1.reshape(nb, b, j_dim)

    # Derive the accumulator initializers from the inputs (instead of
    # plain constants) so that under shard_map they carry the same
    # varying-axis type as the per-block contributions — a constant
    # init would make the scan carry type-mismatch on a sharded mesh.
    zero = d[:, :1] * (a1[:1, :] * 0)
    init = (zero, zero, zero)

    def body(carry, blk):
        m_a, m_f, m_t = carry
        db, a1b, f1b, t1b = blk
        dx = db[:, :, None]                                  # [R, B, 1]
        m_a = jnp.maximum(m_a, (dx * a1b[None]).max(1))
        m_f = jnp.maximum(m_f, (dx * f1b[None]).max(1))
        m_t = jnp.maximum(m_t, (dx * t1b[None]).max(1))
        return (m_a, m_f, m_t), None

    (m_a, m_f, m_t), _ = lax.scan(
        body, init, (d_blocks, a1_blocks, f1_blocks, t1_blocks))
    return m_a - 1, m_f - 1, m_t - 1, m_t > 0


def _masked_max_mxu(d_i8, v):
    """``m[r, j] = max over s with d[r, s] of v[s, j]`` (0 if none) —
    exact, by MXU level decomposition.

    The (max, select) semiring cannot ride the MXU directly, but its
    *levels* can: per iteration, the per-column candidate value
    ``cur[j]`` (starting at the column max) defines a witness mask
    ``W[s, j] = (v[s, j] == cur[j])``, and one boolean matmul
    ``d @ W > 0`` resolves every receiver whose delivery set contains a
    witness.  Unresolved (r, j) cells descend to the next distinct
    value.  Real heartbeat columns concentrate on a handful of
    distinct values, so the ``while_loop`` typically runs 1-4
    iterations — each a 0/1 matmul (s8 x s8 -> s32: exact, and 2x
    the bf16 MXU rate with 4x less operand traffic) plus O(N²)
    elementwise work — instead of the O(N³) VPU product-max.

    Two in-vivo pathologies are cut off up front by a pre-resolve
    matmul ``d @ (v > 0)``: receivers with NO contributing sender for
    a column are done immediately (their max is the 0 FILL encoding)
    instead of descending through every distinct stale value — after
    a failure wave freezes half the columns, or when message drops
    spread the fresh-timestamp columns over up to ``t_remove``
    distinct per-tick values, the descent otherwise runs 10-20 levels
    (measured ~16 ms/tick of witness matmuls at the dense N=4096 drop
    config).
    """
    cur = v.max(0)
    # derive the carry initializers from the inputs (not plain
    # constants) so that under shard_map they carry the same
    # varying-axis type as the loop body's outputs — same workaround
    # as gossip_reductions' scan init below
    m = (d_i8[:, :1] * 0).astype(v.dtype) + v[:1, :] * 0       # (R, J)
    # witness matmuls run in int8 (s8 x s8 -> s32 on the MXU: 2x the
    # bf16 rate and 4x less operand traffic; exact — counts <= S)
    has_any = lax.dot_general(d_i8, (v > 0).astype(jnp.int8),
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32) > 0
    done = ~has_any

    def cond(c):
        m, cur, done = c
        return (~done).any() & (cur > 0).any()

    def body(c):
        m, cur, done = c
        w = ((v == cur[None, :]) & (cur > 0)[None, :]).astype(jnp.int8)
        hit = lax.dot_general(d_i8, w, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32) > 0
        newly = hit & ~done
        m = jnp.where(newly, cur[None, :], m)
        done = done | newly | (cur == 0)[None, :]
        v_lt = jnp.where(v < cur[None, :], v, 0)
        return m, v_lt.max(0), done

    m, _, _ = lax.while_loop(cond, body, (m, cur, done))
    return m


@partial(jax.jit, static_argnames=("t_remove", "block_size"))
def gossip_reductions_mxu(recv_from, known, hb, ts, now, *,
                          t_remove: int, block_size: int = 128):
    """Same contract as :func:`gossip_reductions`, computed by MXU
    level decomposition (:func:`_masked_max_mxu`) instead of the
    blockwise VPU product-max.  Bit-identical outputs
    (tests/test_pallas.py::test_mxu_reductions_match); measured ~2x
    the end-to-end dense-tick throughput at N=512 on v5e.
    ``block_size`` is accepted for interface parity and ignored.
    """
    a1, f1, t1 = merge_payloads(known, hb, ts, now, t_remove)
    d = recv_from.astype(jnp.int8)
    # separate per-plane loops: each plane runs only ITS OWN level
    # count (sum-of-levels (S, J) matmuls beats max-of-levels (S, 3J)
    # ones whenever the level counts are uneven, which is the in-vivo
    # case — the timestamp plane needs ~3-6x the heartbeat planes'
    # levels under drops)
    m_a = _masked_max_mxu(d, a1)
    m_f = _masked_max_mxu(d, f1)
    m_t = _masked_max_mxu(d, t1)
    return m_a - 1, m_f - 1, m_t - 1, m_t > 0
