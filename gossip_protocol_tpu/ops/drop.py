"""Message-loss injection as a jit-able Bernoulli mask.

Replaces ``EmulNet::ENsend``'s drop check (EmulNet.cpp:90-94):
``rand() % 100 < MSG_DROP_PROB * 100`` while the ``dropmsg`` window is
open.  The reference's ``srand(time(NULL))`` (Application.cpp:50,96)
makes runs irreproducible; here the masks come from a per-tick
``jax.random`` key so every run is replayable from the config seed.

One (N+2, N) uniform draw covers every send class of a tick — gossip
lattice rows, JOINREQ vector, JOINREP vector — so the whole tick costs
a single PRNG kernel, and the draw is skipped entirely outside the drop
window (a ``lax.cond`` on the window flag).  The gossip rows are keyed
by *global* sender index, so a sharded tick slices its local rows out
of the identical lattice and the single-device and multi-device paths
produce bit-identical drop patterns (testing/dropsync.py replays the
same draw for the differential oracle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def tick_drop_masks(rng: jax.Array, t: jax.Array, n: int, active, prob,
                    link_prob=None):
    """Per-tick drop decisions for all three send classes.

    Args:
      rng:    the run's PRNG key (tick index is folded in here).
      t:      i32 scalar — current tick.
      n:      peer count (static).
      active: bool scalar — is the drop window open for this tick's
        sends?  (dropmsg is set after tick 50 and cleared after tick
        300, Application.cpp:177-200, so sends during ticks [51, 300]
        are droppable.)
      prob:   f32 scalar drop probability (MSG_DROP_PROB).
      link_prob: optional f32[N, N] per-link probability matrix
        (sender-major; the asym_drop world, worlds.py) replacing the
        uniform ``prob`` — the JOINREQ row uses each sender's link to
        the introducer, the JOINREP row the introducer's link to each
        receiver.  Same single draw, same ``lax.cond`` on the window.

    Returns:
      gossip_drop bool[N, N] (sender-major), joinreq_drop bool[N],
      joinrep_drop bool[N].
    """
    if link_prob is None:
        thr = prob
    else:
        from ..config import INTRODUCER
        thr = jnp.concatenate([
            link_prob,
            link_prob[:, INTRODUCER][None, :],   # JOINREQ i -> intro
            link_prob[INTRODUCER][None, :],      # JOINREP intro -> j
        ], 0)

    def draw(_):
        u = jax.random.uniform(jax.random.fold_in(rng, t), (n + 2, n))
        return u < thr

    drop = lax.cond(active, draw,
                    lambda _: jnp.zeros((n + 2, n), bool), None)
    return drop[:n], drop[n], drop[n + 1]
