"""Message-loss injection as a jit-able Bernoulli mask.

Replaces ``EmulNet::ENsend``'s drop check (EmulNet.cpp:90-94):
``rand() % 100 < MSG_DROP_PROB * 100`` while the ``dropmsg`` window is
open.  The reference's ``srand(time(NULL))`` (Application.cpp:50,96)
makes runs irreproducible; here the mask comes from a per-tick
``jax.random`` key so every run is replayable from the config seed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def drop_mask(key: jax.Array, shape, active, prob) -> jax.Array:
    """bool mask: True where a send is dropped.

    Args:
      key:    per-tick PRNG key (fold the tick index into the run key).
      shape:  shape of the send lattice to mask.
      active: bool scalar — is the drop window open for this tick's
        sends?  (dropmsg is set after tick 50 and cleared after tick
        300, Application.cpp:177-200, so sends during ticks [51, 300]
        are droppable.)
      prob:   f32 scalar drop probability (MSG_DROP_PROB).
    """
    return active & (jax.random.uniform(key, shape) < prob)
