"""World state: the entire simulation as a handful of device arrays.

The reference scatters per-peer state across N heap-allocated ``Member``
objects (Member.h:89-122), each holding a ``vector<MemberListEntry>``
(Member.h:62-81) and an inbox queue, plus a shared in-flight message
buffer (EmulNet.h:35-72).  Here the same information is a single pytree
of dense arrays, batched over the peer axis, so one simulation tick is
one XLA program:

* ``known[i, j]``  — peer *i*'s member list contains peer *j*
  (replaces ``vector<MemberListEntry>`` membership).
* ``hb[i, j]``     — the heartbeat value *i* has recorded for *j*
  (``MemberListEntry::heartbeat``, Member.h:66).
* ``ts[i, j]``     — the local-clock timestamp of *i*'s entry for *j*
  (``MemberListEntry::timestamp``, Member.h:67).
* ``in_group[i]``  — ``Member::inGroup`` (Member.h:95).
* ``own_hb[i]``    — ``Member::heartbeat`` (Member.h:101).  Write-only in
  the reference too: the sender's own heartbeat is never transmitted
  (MP1Node.cpp:355-358 sends only the member list, which excludes self);
  receivers *increment* their own counter for the sender instead
  (MP1Node.cpp:236-239).  Kept for parity and metrics.
* ``gossip[s, r]`` — a GOSSIP message from *s* to *r* is in flight
  (sent during the previous tick, consumed this tick).  The payload is
  *s*'s row of ``known/hb/ts`` — which is exactly the carried state from
  the end of the previous tick, so no copy is needed.  This replaces the
  EmulNet buffer (EmulNet.h:35-72) for gossip traffic.
* ``joinreq[i]``   — peer *i*'s JOINREQ to the introducer is in flight
  (MP1Node.cpp:135-149).
* ``joinrep[i]``   — a JOINREP to peer *i* is in flight (MP1Node.cpp:225-229).
* ``rng``          — PRNG key for the drop mask; replaces ``rand()``
  (EmulNet.cpp:90) with a per-tick folded key so runs are reproducible.

Timestamps use the global logical clock (``Params::getcurrtime``,
Params.cpp:48-50); all peers share it, as in the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from .config import INTRODUCER, SimConfig


@struct.dataclass
class WorldState:
    """Carried state of the simulation (one pytree node per array above)."""

    tick: jax.Array      # i32 scalar — the global logical clock
    in_group: jax.Array  # bool[N]
    own_hb: jax.Array    # i32[N]
    known: jax.Array     # bool[N, N]
    hb: jax.Array        # i32[N, N]
    ts: jax.Array        # i32[N, N]
    gossip: jax.Array    # bool[N, N]  (sender, receiver)
    gossip_age: jax.Array  # i32[N, N] — ticks the in-flight message has
                           #   already waited (latency plane, worlds.py;
                           #   all-zero and carried inert when
                           #   link_latency == 0: every link then
                           #   delivers after the reference's one tick)
    joinreq: jax.Array   # bool[N]
    joinrep: jax.Array   # bool[N]
    rng: jax.Array       # PRNG key

    @property
    def n(self) -> int:
        return self.known.shape[0]


@struct.dataclass
class Schedule:
    """Per-run injection schedule, precomputed on host.

    Replaces ``Application::fail`` (Application.cpp:173-202) and the
    staggered introduction logic (Application.cpp:143-148) with data:
    the tick function consumes these arrays instead of branching on
    host-side RNG.
    """

    start_tick: jax.Array   # i32[N] — node i introduced at this tick (Application.cpp:143)
    fail_tick: jax.Array    # i32[N] — bFailed flips at the END of this tick
                            #          (fail() runs after mp1Run, Application.cpp:99-104);
                            #          a huge sentinel means "never fails"
    rejoin_tick: jax.Array  # i32[N] — churn extension (absent in the reference,
                            #          SURVEY.md §5): a failed peer is wiped and
                            #          re-introduced at this tick, rejoining
                            #          through the normal JOINREQ path; NEVER
                            #          sentinel = stays dead
    drop_active: jax.Array  # bool[T] — dropmsg flag value during tick t's sends
    drop_prob: jax.Array    # f32 scalar — MSG_DROP_PROB
    drop_open: jax.Array    # i32 scalar — EXACT drop window of this lane:
    drop_close: jax.Array   #   open < t <= close ((-1, -2) = no window).
                            #   Redundant with drop_active for solo runs;
                            #   the canonical fleet path (service/canonical
                            #   .py) shares a QUANTIZED superset window as
                            #   drop_active across lanes and re-applies the
                            #   exact window from these scalars, per lane,
                            #   after the draw (make_tick lane_drop_window)
    # --- adversarial failure worlds (worlds.py); every field below is
    # --- inert data (zeros / empty) when its world is off ---
    part_group: jax.Array   # i32[N] — hashed partition group per node
    part_on: jax.Array      # bool scalar — partition world configured
    part_open: jax.Array    # i32 — cross-group sends blocked:
    part_close: jax.Array   # i32   open < t <= close
    link_prob: jax.Array    # f32[N, N] per-link drop probability
                            #   (sender-major; f32[0, 0] when asym off —
                            #   the tick branches statically on the cfg)
    flap_mask: jax.Array    # bool[N] — which nodes flap
    flap_phase: jax.Array   # i32[N] — absolute cycle anchor per node
    flap_period: jax.Array  # i32 scalar
    flap_down: jax.Array    # i32 scalar — down ticks per period
    flap_close: jax.Array   # i32 scalar — last tick a cycle may end at
    byz_mask: jax.Array     # bool[N] — seeded liars (byz plane; zeros
                            #   when off — the tick branches statically)
    byz_target: jax.Array   # bool[N, N] — liar row i ghost-advertises
                            #   ids j (bool[0, 0] when the plane is off)
    byz_boost: jax.Array    # i32 scalar — relayed-heartbeat inflation
    link_lat: jax.Array     # i32[N, N] per-link delivery delay in
                            #   ticks (sender-major; i32[0, 0] when the
                            #   latency plane is off)

    def _flap_state(self, t: jax.Array):
        """(failed, rejoining) bool[N] under the flap world: a flapper
        is down for positions [1, flap_down] of every cycle from its
        anchor and rejoins (fresh-nodeStart wipe, like churn) at
        position flap_down — only for cycles completing before
        ``flap_close``, so the window always ends clean."""
        per = jnp.maximum(self.flap_period, 1)
        pos = t - self.flap_phase
        c = pos // per
        off = pos - c * per
        ok = self.flap_mask & (pos >= 1) \
            & (self.flap_phase + c * per + self.flap_down
               <= self.flap_close)
        return (ok & (off >= 1) & (off <= self.flap_down),
                ok & (off == self.flap_down))

    def failed_at(self, t: jax.Array) -> jax.Array:
        """bool[N]: is peer i failed while processing tick ``t``?

        ``fail()`` flips ``bFailed`` after tick ``fail_tick`` completes
        (Application.cpp:99-104,181-196), so the flag is observed from
        tick ``fail_tick + 1`` on.  A churned peer is failed only for
        the window ``fail_tick < t <= rejoin_tick`` (its rejoin acts
        like a fresh ``nodeStart`` at ``rejoin_tick``).  Flapping
        members (worlds.py) add their periodic down phases on top.
        """
        f, _ = self._flap_state(t)
        return ((t > self.fail_tick) & (t <= self.rejoin_tick)) | f

    def window_failed_at(self, t: jax.Array) -> jax.Array:
        """bool[N]: the WINDOW component of :meth:`failed_at` alone
        (scripted / churn / wave — no flap).  The zombie world applies
        to exactly these failures: a zombie keeps gossiping its frozen
        table through its whole fail window, while a flap down-phase
        is an ordinary silence."""
        return (t > self.fail_tick) & (t <= self.rejoin_tick)

    def rejoining_at(self, t: jax.Array) -> jax.Array:
        """bool[N]: peers wiped and re-introduced at tick ``t`` (the
        churn/rejoin path, plus every flap up-edge)."""
        _, r = self._flap_state(t)
        return (t == self.rejoin_tick) | r

    def part_active_at(self, t: jax.Array) -> jax.Array:
        """bool scalar: are cross-group sends blocked at tick ``t``?"""
        return self.part_on & (t > self.part_open) & (t <= self.part_close)


NEVER = np.iinfo(np.int32).max  # sentinel fail_tick for peers that never fail


def make_schedule_host(cfg: SimConfig) -> Schedule:
    """:func:`make_schedule` with pure NUMPY leaves — zero eager
    device ops.  The fleet serving path stages lane schedules with
    this (core/fleet.py): on the pipelined dispatch path a fleet
    program is often in flight, and eager jnp staging either blocks
    at the client's bounded in-flight queue or costs device
    round-trips per lane; host leaves enter device code as ordinary
    jit-call inputs.  NOT for code that closes over the schedule
    inside a traced function (a numpy ``drop_active`` indexed by a
    traced tick raises) — that is what :func:`make_schedule` is for.

    Mirrors ``Application::fail`` semantics exactly:

    * single failure: one uniformly random victim at ``fail_tick``
      (Application.cpp:181-187);
    * multi failure: a contiguous block ``[r, r + N/2)`` with
      ``r = (rand() % N) / 2`` (C precedence, Application.cpp:189-190);
    * drop window: the ``dropmsg`` flag is set *after* tick 50 and
      cleared *after* tick 300 (Application.cpp:177-179,198-200), so
      sends are droppable for ticks in ``[51, 300]`` inclusive.

    Victim selection draws from the counter-based hash PRNG shared with
    the native engine (utils/prng.py == native/engine.cc), so the same
    seed yields the same schedule on every backend.
    """
    from .utils.prng import fail_schedule_uniform

    from . import worlds

    n = cfg.n
    start = np.array([cfg.start_tick(i) for i in range(n)], np.int32)
    if cfg.wave_size > 0:
        # correlated failure wave: a seeded epicenter + radius ramp
        # replaces the scripted single/multi draw (worlds.py)
        fail = worlds.wave_fail_ticks(cfg)
    else:
        fail = np.full(n, NEVER, np.int32)
        u = fail_schedule_uniform(cfg.seed)
        if cfg.single_failure:
            victim = int(u * n) % n
            fail[victim] = cfg.fail_tick
        else:
            r = (int(u * n) % n) // 2
            fail[r: r + n // 2] = cfg.fail_tick
    rejoin = np.full(n, NEVER, np.int32)
    if cfg.rejoin_after is not None:
        if cfg.rejoin_after < 1:
            # rejoin_tick == fail_tick would collapse the failed window
            # (failed_at never true) and the rejoin wipe would race the
            # peer's own tick processing
            raise ValueError("rejoin_after must be >= 1")
        failed = fail != NEVER
        rejoin[failed] = fail[failed] + cfg.rejoin_after
    t = np.arange(cfg.total_ticks, dtype=np.int32)
    drop = np.zeros(cfg.total_ticks, bool)
    if cfg.drop_msg:
        drop = (t > cfg.drop_open_tick) & (t <= cfg.drop_close_tick)
    part_open, part_close = worlds.partition_window(cfg)
    _, flap_close = worlds.flap_window(cfg)
    return Schedule(
        start_tick=start,
        fail_tick=fail,
        rejoin_tick=rejoin,
        drop_active=drop,
        drop_prob=np.float32(cfg.msg_drop_prob),
        drop_open=np.int32(cfg.drop_open_tick if cfg.drop_msg else -1),
        drop_close=np.int32(cfg.drop_close_tick if cfg.drop_msg else -2),
        part_group=worlds.partition_groups_host(cfg),
        part_on=np.bool_(cfg.partition_groups >= 2),
        part_open=np.int32(part_open),
        part_close=np.int32(part_close),
        link_prob=worlds.link_prob_host(cfg),
        flap_mask=worlds.flap_mask_host(cfg),
        flap_phase=worlds.flap_anchor_host(cfg),
        flap_period=np.int32(max(cfg.flap_period, 1)),
        flap_down=np.int32(cfg.flap_down),
        flap_close=np.int32(flap_close if cfg.flap_rate > 0 else -1),
        byz_mask=worlds.byz_mask_host(cfg),
        byz_target=worlds.byz_target_host(cfg),
        byz_boost=np.int32(cfg.byz_boost),
        link_lat=worlds.link_latency_host(cfg),
    )


def make_schedule(cfg: SimConfig) -> Schedule:
    """Build the injection schedule for a scenario (device leaves).

    See :func:`make_schedule_host` for the numpy-leaf variant; this
    one wraps the leaves in jnp arrays so consumers that CLOSE OVER
    the schedule inside traced code keep working.
    """
    s = make_schedule_host(cfg)
    return jax.tree.map(jnp.asarray, s)


def slice_schedule(s: Schedule, a: int) -> Schedule:
    """Width-``a`` view of the per-node schedule fields (window
    scalars shared) — the active-corner paths (core/dense_corner.py,
    the fleet bench staging) run on the leading ``a``-peer block, so
    their schedules slice the same block.  The corner is gated off for
    world configs (dense_corner.active_bound), so the world fields
    sliced here are always inert."""
    return s.replace(
        start_tick=s.start_tick[:a], fail_tick=s.fail_tick[:a],
        rejoin_tick=s.rejoin_tick[:a],
        part_group=s.part_group[:a], link_prob=s.link_prob[:a, :a],
        flap_mask=s.flap_mask[:a], flap_phase=s.flap_phase[:a],
        byz_mask=s.byz_mask[:a], byz_target=s.byz_target[:a, :a],
        link_lat=s.link_lat[:a, :a])


def pad_schedule_host(s: Schedule, width: int) -> Schedule:
    """Embed a width-``n`` schedule into a width-``width`` one with
    INERT filler rows — the peer-axis generalization of the fleet's
    filler lanes (service/canonical.py pad-ladder).  Filler peers get
    ``start_tick = NEVER``: they are never introduced, never send a
    JOINREQ, are never known by anyone, and their state rows stay
    identically zero for the whole run, so the real ``n x n`` corner
    of a padded run is bit-identical to the unpadded run
    (tests/test_canonical.py pins this per tick).  Matrix world
    planes pad with values that are dead by construction (no send
    ever leaves the real corner): ``link_prob`` 0, ``byz_target``
    False, ``link_lat`` 1.  Window scalars and ``drop_active`` are
    width-independent and pass through.  Host numpy only."""
    n = int(s.start_tick.shape[0])
    if width == n:
        return s
    if width < n:
        raise ValueError(f"pad width {width} < schedule width {n}")

    def vec(a, fill):
        out = np.full((width,), fill, np.asarray(a).dtype)
        out[:n] = a
        return out

    def plane(a, fill):
        a = np.asarray(a)
        if a.size == 0:          # (0, 0) placeholder: plane is off
            return a
        out = np.full((width, width), fill, a.dtype)
        out[:n, :n] = a
        return out

    return s.replace(
        start_tick=vec(s.start_tick, NEVER),
        fail_tick=vec(s.fail_tick, NEVER),
        rejoin_tick=vec(s.rejoin_tick, NEVER),
        part_group=vec(s.part_group, 0),
        link_prob=plane(s.link_prob, 0.0),
        flap_mask=vec(s.flap_mask, False),
        flap_phase=vec(s.flap_phase, 0),
        byz_mask=vec(s.byz_mask, False),
        byz_target=plane(s.byz_target, False),
        link_lat=plane(s.link_lat, 1))


def init_state(cfg: SimConfig) -> WorldState:
    """Fresh world state at tick 0 (before anything has happened).

    Matches ``MP1Node::initThisNode`` (MP1Node.cpp:95-113): empty member
    lists, heartbeat 0, nobody in-group; the introducer only joins the
    group when its start tick fires inside the tick function
    (MP1Node.cpp:126-132).
    """
    n = cfg.n
    return WorldState(
        tick=jnp.int32(0),
        in_group=jnp.zeros(n, bool),
        own_hb=jnp.zeros(n, jnp.int32),
        known=jnp.zeros((n, n), bool),
        hb=jnp.zeros((n, n), jnp.int32),
        ts=jnp.zeros((n, n), jnp.int32),
        gossip=jnp.zeros((n, n), bool),
        gossip_age=jnp.zeros((n, n), jnp.int32),
        joinreq=jnp.zeros(n, bool),
        joinrep=jnp.zeros(n, bool),
        rng=jax.random.PRNGKey(cfg.seed),
    )


def struct_to_host(state) -> dict[str, np.ndarray]:
    """Any state struct -> plain numpy dict (checkpointing/debugging)."""
    return {f.name: np.asarray(getattr(state, f.name))
            for f in dataclasses.fields(type(state))}


def struct_from_host(host: dict[str, np.ndarray], cls, expect_shapes):
    """Rebuild a state struct from a host dict, schema-checked.

    ``expect_shapes(host) -> {field: shape}`` derives the expected
    geometry from the checkpoint itself (e.g. its peer count).
    """
    names = {f.name for f in dataclasses.fields(cls)}
    missing = names - host.keys()
    if missing:
        raise ValueError(f"checkpoint is missing fields: {sorted(missing)}")
    extra = host.keys() - names
    if extra:
        raise ValueError(
            f"checkpoint has unknown fields {sorted(extra)} — written by an "
            f"incompatible {cls.__name__} schema?")
    for k, shape in expect_shapes(host).items():
        got = np.asarray(host[k]).shape
        if got != shape:
            raise ValueError(
                f"checkpoint field {k!r} has shape {got}, expected {shape}")
    return cls(**{k: jnp.asarray(host[k]) for k in names})


def save_struct_checkpoint(state, path: str) -> None:
    """Write a mid-run checkpoint (.npz) of a state struct.

    The path is used verbatim (np.savez would append ".npz" to an
    extension-less path, breaking the save/load round trip).
    """
    with open(path, "wb") as f:
        np.savez(f, **struct_to_host(state))


def load_struct_checkpoint(path: str, cls, expect_shapes):
    with np.load(path) as z:
        return struct_from_host({k: z[k] for k in z.files}, cls,
                                expect_shapes)


def _world_expect(host):
    n = np.asarray(host["known"]).shape[0]
    return {"tick": (), "in_group": (n,), "own_hb": (n,),
            "known": (n, n), "hb": (n, n), "ts": (n, n),
            "gossip": (n, n), "gossip_age": (n, n),
            "joinreq": (n,), "joinrep": (n,)}


def state_to_host(state: WorldState) -> dict[str, np.ndarray]:
    """Device state -> plain numpy dict (for checkpointing / debugging)."""
    return struct_to_host(state)


def state_from_host(host: dict[str, np.ndarray]) -> WorldState:
    """Inverse of :func:`state_to_host`: rebuild device state.

    The reference has no checkpointing at all (runs are always 0..700,
    Application.cpp:99); here the whole world is one pytree of arrays,
    so restore is a straight upload.  Continuation is bit-identical
    because the clock, the in-flight traffic, and the PRNG key are all
    part of the state (tests/test_checkpoint.py).
    """
    return struct_from_host(host, WorldState, _world_expect)


def save_checkpoint(state: WorldState, path: str) -> None:
    """Write a mid-run checkpoint (.npz) of the full simulation state."""
    save_struct_checkpoint(state, path)


def load_checkpoint(path: str) -> WorldState:
    """Load a checkpoint written by :func:`save_checkpoint`."""
    return load_struct_checkpoint(path, WorldState, _world_expect)
