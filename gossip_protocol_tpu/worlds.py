"""Closed-form adversarial failure worlds, shared by both models.

The course's scenario vocabulary is three worlds — single fail, multi
fail, 10% uniform drop — plus the churn extension, while the protocol
family this framework reproduces (SWIM-style membership, accrual
failure detectors) is evaluated in the literature under partitions,
correlated failures, message-loss asymmetry, and flapping members.
This module is the single source of truth for those richer worlds:
every draw is a pure counter-hash function of ``(seed, tick, node)``
(utils/hash32.mix32), exactly like the existing churn/drop machinery,
so

* the dense model (state.make_schedule_host) precomputes them into
  Schedule arrays,
* the overlay model (models/overlay.OverlaySchedule) evaluates them
  in traced code with zero lookup tables,
* the numpy oracle (testing/overlay_oracle.py) replays them
  bit-exactly,
* fleet lanes stay bit-replayable: seeds move *which* nodes are hit,
  never the windows — the windows are seed-independent config
  functions, which is what lets them ride the segment planner
  (models/segments.phase_windows) and the service bucket keys
  unchanged.

The five worlds (config knobs on :class:`~.config.SimConfig`):

* **partition** (``partition_groups >= 2``) — every node is hashed
  into one of G groups; during ``(partition_open_tick,
  partition_close_tick]`` cross-group sends are blocked (gossip,
  JOINREQ, JOINREP alike — the gate rides the drop plane, applied at
  send time like a drop decision).  Healing is the window closing.
* **asymmetric per-link drop** (``asym_drop``) — the single uniform
  ``msg_drop_prob`` becomes a direction- and pair-dependent matrix:
  link (i -> j) drops with probability ``U(seed, i*N+j) * 2p`` (mean
  ``p``), so some links are near-clean and some lose ~2p of traffic.
  Active during the ordinary drop window.
* **correlated failure wave** (``wave_size > 0``) — a seeded
  epicenter plus a radius-per-tick ramp: the ``wave_size`` nodes in
  the contiguous ring block starting at the epicenter fail at
  ``wave_start + offset // wave_speed`` — k failures within a short
  window instead of independent draws.  Replaces the scripted
  single/multi failure (like churn does); composes with
  ``rejoin_after``.
* **zombie / stale-table peers** (``zombie``) — a window-failed peer
  keeps gossiping its frozen table and frozen heartbeat after its
  fail tick.  Receivers treat the frozen heartbeat as what it is —
  an old observation (its liveness claim is timestamped at the fail
  tick, not the send tick) — so detection still completes, and the
  stale table must not resurrect removed members (the false-positive
  stress the world exists for).
* **flapping members** (``flap_rate > 0``) — a hashed subset of nodes
  fail and rejoin periodically inside ``[flap_open, flap_close]``
  with a closed-form duty cycle: each flapper's cycle anchor is
  ``flap_open + H(seed, i) % flap_period``, it is down for
  ``flap_down`` ticks of every period (only cycles that complete
  before ``flap_close`` run), and every up-edge re-enters through the
  normal JOINREQ path.

Round 2 adds the two planes the first five could not express — no
world FORGED information and no link had LATENCY — plus the
composition grammar (:func:`composition`) that makes the planes
multiply instead of add:

* **Byzantine forgery** (``byz_rate > 0``) — a hashed subset of liars
  inflate their own heartbeat counter, relay their tables at forged
  freshness with heartbeats boosted by ``byz_boost``, and ghost-
  advertise a hashed quarter of the id space (fake members, removed
  victims — the resurrection-pressure attack).  The defense compiles
  in with the plane: liveness evidence is DIRECT-ONLY — a relayed
  heartbeat updates the counter but never refreshes the staleness
  timestamp, and a relayed new entry starts its staleness clock on
  arrival — so honest detection completes on the unchanged horizon
  and every forged entry is purged within ``t_remove + 1`` of its
  last advertisement (the closed-form false-positive bound).
* **per-link latency** (``link_latency > 0``) — link (i -> j)
  delivers gossip after ``1 + H(seed, i*n+j, SALT_LAT) %
  (link_latency + 1)`` ticks (the asym-drop construction with a delay
  codomain).  Needs a message-age dimension in the tick: the dense
  model ages its in-flight gossip plane (``WorldState.gossip_age``,
  at most one message in flight per link), the overlay keeps a
  send-history bitmask (``OverlayState.send_hist``).  Latency delays
  the DELIVERY event; the payload rides the sender's current table
  (the zero-copy discipline both models share), and the join path
  stays one-tick so the segment planner's join windows are untouched.
"""

from __future__ import annotations

import numpy as np

from .config import INTRODUCER, SimConfig
from .utils.hash32 import mix32, threshold32

#: counter-hash salts for the world streams (1-8 are taken by the
#: overlay's mask/drop/churn/slot/degree streams, models/overlay.py)
SALT_LINK = 9         # per-link drop threshold (asym_drop)
SALT_PART = 10        # partition group assignment
SALT_FLAP = 11        # flapping-member selection
SALT_FLAP_PHASE = 12  # per-flapper cycle anchor
SALT_WAVE = 13        # wave epicenter
SALT_BYZ = 14         # Byzantine liar selection (round 2)
SALT_BYZ_TARGET = 15  # per-liar ghost-advertisement targets
SALT_LAT = 16         # per-link delivery delay (round 2)

_U = np.uint32


# ---- resolved windows (seed-independent config functions) ----------

def wave_start(cfg: SimConfig) -> int:
    """Absolute tick the wave's epicenter fails (-1 knob = fail_tick)."""
    return cfg.fail_tick if cfg.wave_tick < 0 else cfg.wave_tick


def wave_last_fail(cfg: SimConfig) -> int:
    """Last tick any wave victim fails (the radius ramp's end)."""
    return wave_start(cfg) + (cfg.wave_size - 1) // max(cfg.wave_speed, 1)


def flap_window(cfg: SimConfig) -> tuple[int, int]:
    """Resolved ``[flap_open, flap_close]`` (the -1 knobs default to
    the churn machinery's quarter points)."""
    lo = cfg.total_ticks // 4 if cfg.flap_open_tick < 0 \
        else cfg.flap_open_tick
    hi = (3 * cfg.total_ticks) // 4 if cfg.flap_close_tick < 0 \
        else cfg.flap_close_tick
    return lo, hi


def partition_window(cfg: SimConfig) -> tuple[int, int]:
    """Droppable cross-group sends: ``open < t <= close`` (the same
    half-open convention as the drop window)."""
    return cfg.partition_open_tick, cfg.partition_close_tick


# ---- host-side draws (numpy; the dense Schedule arrays) ------------

def wave_center(cfg: SimConfig) -> int:
    """Seeded epicenter of the correlated failure wave."""
    return int(mix32(_U(cfg.seed & 0xFFFFFFFF), _U(0), _U(SALT_WAVE))) \
        % cfg.n


def wave_fail_ticks(cfg: SimConfig) -> np.ndarray:
    """i32[N] wave fail tick per node (NEVER outside the victim
    block).  Victims are the ``wave_size`` ids in the contiguous ring
    block from the epicenter (introducer excluded — its failure would
    suspend the join path, which is the churn rule too); the node at
    ring offset ``d`` fails at ``wave_start + d // wave_speed``."""
    from .state import NEVER
    n = cfg.n
    off = (np.arange(n) - wave_center(cfg)) % n
    victim = (off < cfg.wave_size) & (np.arange(n) != INTRODUCER)
    t0 = wave_start(cfg)
    return np.where(victim, t0 + off // max(cfg.wave_speed, 1),
                    NEVER).astype(np.int32)


def partition_groups_host(cfg: SimConfig) -> np.ndarray:
    """i32[N] hashed group id per node (zeros when the world is off)."""
    n = cfg.n
    if cfg.partition_groups < 2:
        return np.zeros(n, np.int32)
    g = mix32(_U(cfg.seed & 0xFFFFFFFF),
              np.arange(n, dtype=np.uint32), _U(SALT_PART))
    return (g % _U(cfg.partition_groups)).astype(np.int32)


def link_prob_host(cfg: SimConfig) -> np.ndarray:
    """f32[N, N] per-link drop probability (sender-major), mean
    ``msg_drop_prob``; a f32[0, 0] placeholder when asym_drop is off
    (the tick branches statically, so the field is never read)."""
    if not cfg.asym_drop:
        return np.zeros((0, 0), np.float32)
    n = cfg.n
    i = np.arange(n, dtype=np.uint32)
    # i*N+j wraps in uint32 at very large N — deliberate: it is a hash
    # input, and both backends wrap identically
    h = mix32(_U(cfg.seed & 0xFFFFFFFF),
              i[:, None] * _U(n) + i[None, :], _U(SALT_LINK))
    return (h.astype(np.float64) / 4294967296.0
            * 2.0 * cfg.msg_drop_prob).astype(np.float32)


def flap_threshold(cfg: SimConfig) -> int:
    return threshold32(cfg.flap_rate) if cfg.flap_rate > 0 else 0


def flap_mask_host(cfg: SimConfig) -> np.ndarray:
    """bool[N]: which nodes flap (introducer never — its down phases
    would drop every rejoin's JOINREQ)."""
    n = cfg.n
    if cfg.flap_rate <= 0:
        return np.zeros(n, bool)
    sel = mix32(_U(cfg.seed & 0xFFFFFFFF),
                np.arange(n, dtype=np.uint32), _U(SALT_FLAP)) \
        < _U(flap_threshold(cfg))
    sel = np.asarray(sel, bool).copy()
    sel[INTRODUCER] = False
    return sel


def flap_anchor_host(cfg: SimConfig) -> np.ndarray:
    """i32[N] absolute cycle anchor per node: ``flap_open +
    H(seed, i) % flap_period`` (meaningless where flap_mask is off)."""
    n = cfg.n
    lo, _ = flap_window(cfg)
    ph = mix32(_U(cfg.seed & 0xFFFFFFFF),
               np.arange(n, dtype=np.uint32), _U(SALT_FLAP_PHASE)) \
        % _U(max(cfg.flap_period, 1))
    return (lo + ph.astype(np.int64)).astype(np.int32)


def make_flap_state(cfg: SimConfig):
    """``(i, t) -> (failed, rejoining)`` closure over precomputed
    flap_mask/flap_anchor arrays — the scalar-oracle twin of
    ``Schedule``/``OverlaySchedule`` flap math.  A flapper is down for
    positions [1, flap_down] of each cycle and rejoins at position
    flap_down, cycles running only when they complete before
    flap_close.  Hashes are drawn once here; per-(node, tick) queries
    are O(1), which the message-level oracle relies on (it queries
    every destination every tick)."""
    if cfg.flap_rate <= 0:
        return lambda i, t: (False, False)
    mask = flap_mask_host(cfg)
    anchors = flap_anchor_host(cfg)
    _, hi = flap_window(cfg)
    per = max(cfg.flap_period, 1)
    down = cfg.flap_down

    def state(i: int, t: int) -> tuple[bool, bool]:
        if not bool(mask[i]):
            return False, False
        anchor = int(anchors[i])
        pos = t - anchor
        if pos < 1:
            return False, False
        c = pos // per
        off = pos - c * per
        if anchor + c * per + down > hi:
            return False, False
        return (1 <= off <= down), off == down

    return state


def flap_state_host(cfg: SimConfig, i: int, t: int) -> tuple[bool, bool]:
    """One-shot ``make_flap_state`` query (re-draws the hash arrays;
    use the closure for per-tick loops)."""
    return make_flap_state(cfg)(i, t)


# ---- round-2 planes: Byzantine forgery + per-link latency ----------

#: fraction of ids each liar ghost-advertises (fixed — the knob that
#: matters is byz_rate; a quarter of the id space keeps every receiver
#: under sustained forged-add pressure without drowning the run)
BYZ_TARGET_FRACTION = 0.25


def byz_threshold(cfg: SimConfig) -> int:
    """uint32 threshold for the liar-selection draw."""
    return threshold32(cfg.byz_rate) if cfg.byz_rate > 0 else 0


def byz_mask_host(cfg: SimConfig) -> np.ndarray:
    """bool[N]: which nodes lie (introducer never — a lying join
    authority would forge the membership ground truth itself, which is
    a different protocol's problem; the flap/wave worlds exempt it for
    the same reason)."""
    n = cfg.n
    if cfg.byz_rate <= 0:
        return np.zeros(n, bool)
    sel = mix32(_U(cfg.seed & 0xFFFFFFFF),
                np.arange(n, dtype=np.uint32), _U(SALT_BYZ)) \
        < _U(byz_threshold(cfg))
    sel = np.asarray(sel, bool).copy()
    sel[INTRODUCER] = False
    return sel


def byz_target_host(cfg: SimConfig) -> np.ndarray:
    """bool[N, N] ghost-advertisement targets: liar row i forges
    fresh, boosted entries for the hashed quarter of ids in row i —
    members it may never have heard from, including removed victims
    (the resurrection-pressure attack).  Rows of honest nodes are
    zeroed; a bool[0, 0] placeholder when the plane is off (the tick
    branches statically)."""
    if cfg.byz_rate <= 0:
        return np.zeros((0, 0), bool)
    n = cfg.n
    i = np.arange(n, dtype=np.uint32)
    tgt = mix32(_U(cfg.seed & 0xFFFFFFFF),
                i[:, None] * _U(n) + i[None, :], _U(SALT_BYZ_TARGET)) \
        < _U(threshold32(BYZ_TARGET_FRACTION))
    tgt = np.asarray(tgt, bool) & byz_mask_host(cfg)[:, None]
    np.fill_diagonal(tgt, False)
    return tgt


def link_latency_host(cfg: SimConfig) -> np.ndarray:
    """i32[N, N] per-link delivery delay in ticks (sender-major):
    ``1 + H(seed, i*N+j, SALT_LAT) % (link_latency + 1)``, so every
    link delays in [1, link_latency + 1] and the plane off means the
    reference's uniform one-tick delivery.  An i32[0, 0] placeholder
    when off (the tick branches statically)."""
    if cfg.link_latency <= 0:
        return np.zeros((0, 0), np.int32)
    n = cfg.n
    i = np.arange(n, dtype=np.uint32)
    h = mix32(_U(cfg.seed & 0xFFFFFFFF),
              i[:, None] * _U(n) + i[None, :], _U(SALT_LAT))
    return (1 + h % _U(cfg.link_latency + 1)).astype(np.int32)


def link_latency_of(seed, iu, ju, n: int, link_latency: int):
    """Traced twin of :func:`link_latency_host` for the overlay's
    per-(partner, row) lookups: ``iu``/``ju`` are uint32 id arrays
    (sender, receiver); returns the i32 delay of each link."""
    h = mix32(seed, iu * _U(n) + ju, _U(SALT_LAT))
    return (1 + h % _U(link_latency + 1)).astype("int32")


# ---- the composition grammar ---------------------------------------

#: overlay planes (any subset composes; the failure SCRIPT is chosen
#: exactly-one-of scripted | wave | churn — wave and churn both
#: replace the scripted failure, which config validation enforces)
PLANES = ("partition", "asym", "zombie", "flapping", "byz", "latency")


def composition(cfg: SimConfig) -> tuple[str, tuple[str, ...]]:
    """``(failure_script, active_planes)`` of a config — the world-
    composition grammar in one place.  A composed world is exactly one
    failure script (scripted single/multi fail, a correlated wave, or
    continuous churn) with any subset of the orthogonal planes layered
    on top; "partition opens DURING a failure wave WHILE flappers
    flap" is one SimConfig.  Every plane's window is a seed-
    independent config function, so compositions fold through
    ``segments.phase_windows`` (∪ of the windows), ``worlds_key``
    (tuple of active planes), plan signatures, bucket keys, and
    checkpoint cuts with no per-plane special cases."""
    script = "churn" if cfg.churn_rate > 0 \
        else "wave" if cfg.wave_size > 0 else "scripted"
    active = []
    if cfg.partition_groups >= 2:
        active.append("partition")
    if cfg.asym_drop:
        active.append("asym")
    if cfg.zombie:
        active.append("zombie")
    if cfg.flap_rate > 0:
        active.append("flapping")
    if cfg.byz_rate > 0:
        active.append("byz")
    if cfg.link_latency > 0:
        active.append("latency")
    return script, tuple(active)


#: world parameters that ride as RUNTIME OPERANDS on the canonical
#: fleet path (service/canonical.py): each maps a plane tag to the
#: SimConfig fields whose values flow through Schedule arrays/scalars
#: instead of being baked into the compiled program.  The plane TAG
#: itself stays static (the tick branches on plane on/off booleans,
#: core/tick.make_tick), so "one program per family" means one program
#: per active-plane SET — probabilities, boosts, radii, per-link
#: matrices all become data.  analysis/cache_keys.py audits that every
#: field named here is read by a DATA_FUNCS builder.
OPERAND_WORLD_FIELDS = {
    "drop": ("msg_drop_prob", "drop_open_tick", "drop_close_tick"),
    "part": ("partition_groups", "partition_open_tick",
             "partition_close_tick"),
    "asym": (),                   # per-link matrix is Schedule data
    "wave": ("wave_size", "wave_tick", "wave_speed"),
    "flap": ("flap_rate", "flap_period", "flap_down",
             "flap_open_tick", "flap_close_tick"),
    "byz": ("byz_rate", "byz_boost"),
    "lat": ("link_latency",),
}


def canonical_world_key(cfg: SimConfig, grid: int) -> tuple:
    """The STATIC half of the operand-vs-static world split: the
    active plane tags — exactly the booleans ``core/tick.make_tick``
    bakes — and nothing else.  Every parameter in
    :data:`OPERAND_WORLD_FIELDS` is omitted: it reaches the compiled
    program as a traced operand via the Schedule (``drop_prob``,
    ``byz_boost``, the flap scalars, the ``fail_tick`` wave script,
    the link matrices), so two configs that differ only in those
    values share one canonical program.  The partition and flap
    WINDOWS are operands too (``part_open``/``part_close`` scalars
    and the flap cycle anchors ride per-lane in SCHED_AXES_CANON —
    both planes are deterministic masks computed OUTSIDE the drop
    cond, state.py ``part_active_at``/``_flap_state``), so no window
    appears here at all; the one window that must be class-shared is
    the drop-draw cond's, carried as the quantized ``drop_q`` pair by
    ``quantized_plan_signature`` itself.  ``grid`` is kept in the
    signature so a future plane that does bake a window has its
    quantization step on hand."""
    del grid  # no window rides this key anymore; see docstring
    ws = []
    if cfg.partition_groups >= 2:
        ws.append(("part",))
    if cfg.asym_drop:
        ws.append(("asym",))
    if cfg.wave_size > 0:
        ws.append(("wave",))
    if cfg.zombie:
        ws.append(("zombie",))
    if cfg.flap_rate > 0:
        ws.append(("flap",))
    if cfg.byz_rate > 0:
        ws.append(("byz",))
    if cfg.link_latency > 0:
        ws.append(("lat",))
    return tuple(ws)
