"""JAX version-compat shims (0.4.x <-> newer APIs).

The codebase targets current JAX spellings; this module maps them
onto what the installed release actually provides (the image ships
jax 0.4.37):

* ``jax.ShapeDtypeStruct(..., vma=...)`` — the varying-manual-axes
  annotation does not exist on 0.4.x; dropping it is sound there
  because 0.4.x shard_map does not type values by VMA at all.
* ``jax.shard_map`` — lives at ``jax.experimental.shard_map`` on
  0.4.x, with ``check_rep`` instead of ``check_vma``.  The two checks
  are different machines (replication-rule inference vs VMA typing);
  passing the caller's intent through keeps full checking wherever
  the installed JAX can express it.

The Pallas TPU compiler-params rename is shimmed separately in
``ops/pallas`` (tpu_compiler_params), next to its only users.
"""

from __future__ import annotations

import inspect

import jax

_SDS_HAS_VMA = "vma" in inspect.signature(jax.ShapeDtypeStruct).parameters


def shape_dtype_struct(shape, dtype, vma=()):
    """``jax.ShapeDtypeStruct`` with the vma annotation when supported."""
    if _SDS_HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def shard_map(body, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` on any supported JAX.

    ``check_vma`` maps to 0.4.x's ``check_rep``: both are the
    "verify the body's sharding typing" switch, and every caller here
    disables it only for the pallas-kernel path (whose operand slicing
    trips either checker, per the jax error text's own prescription).
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    # 0.4.x's check_rep is an incomplete checker: it has no
    # replication rule for while_loop (ops/merge.py's level loop) and
    # its own error text prescribes check_rep=False as the workaround,
    # so the old-API fallback always disables it.  Correctness is held
    # by the differential suites (tests/test_sharded.py,
    # tests/test_overlay_sharded.py compare sharded vs local runs
    # bit-for-bit), not by the static checker.
    return _shard_map(body, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
