"""ctypes bindings for the native runtime (libgossip_native.so).

The native layer (``native/``) is the framework's C++ runtime: the
EmulNet-shaped message bus (bus.cc — ENinit/ENsend/ENrecv/ENcleanup
semantics, reference EmulNet.h:92-96), the reference-grammar log sink
(logsink.cc) and the struct-of-arrays protocol engine (engine.cc) that
serves as the CPU-native backend and differential oracle for the JAX
engine.  Build it with ``make`` at the repo root; these bindings load the
shared library and expose the C ABI to Python for tests and tooling.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
LIB_NAME = "libgossip_native.so"


def lib_path() -> str:
    return os.path.join(_REPO_ROOT, LIB_NAME)


def build(quiet: bool = True) -> bool:
    """Build the native runtime via make.  Returns True on success."""
    try:
        res = subprocess.run(["make", LIB_NAME], cwd=_REPO_ROOT,
                             capture_output=quiet, timeout=300)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


_lib = None


def load(auto_build: bool = True):
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(lib_path()) and auto_build and not build():
        return None
    if not os.path.exists(lib_path()):
        return None
    lib = ctypes.CDLL(lib_path())

    lib.gp_run_scenario.restype = ctypes.c_int
    lib.gp_run_scenario.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
        ctypes.c_char_p]
    lib.gp_run_scenario_churn.restype = ctypes.c_int
    lib.gp_run_scenario_churn.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_int, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_char_p]
    lib.gp_run_conf.restype = ctypes.c_int
    lib.gp_run_conf.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_char_p]

    lib.gp_bus_create.restype = ctypes.c_void_p
    lib.gp_bus_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_double,
                                  ctypes.c_uint64]
    lib.gp_bus_destroy.argtypes = [ctypes.c_void_p]
    lib.gp_bus_init.restype = ctypes.c_int
    lib.gp_bus_init.argtypes = [ctypes.c_void_p]
    lib.gp_bus_send.restype = ctypes.c_int
    lib.gp_bus_send.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_int, ctypes.c_int]
    lib.gp_bus_recv.restype = ctypes.c_int
    lib.gp_bus_recv.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_void_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_int), ctypes.c_int,
                                ctypes.POINTER(ctypes.c_int)]
    lib.gp_bus_inflight.restype = ctypes.c_int
    lib.gp_bus_inflight.argtypes = [ctypes.c_void_p]
    lib.gp_bus_cleanup.restype = ctypes.c_int
    lib.gp_bus_cleanup.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.gp_bus_counters.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_uint32),
                                    ctypes.POINTER(ctypes.c_uint32)]
    lib.gp_hash_uniform.restype = ctypes.c_double
    lib.gp_hash_uniform.argtypes = [ctypes.c_uint64] * 5

    _lib = lib
    return lib


def _require_lib():
    lib = load()
    if lib is None:
        raise RuntimeError(
            "native library unavailable — run `make libgossip_native.so` at "
            "the repo root (needs g++)")
    return lib


def run_scenario(n: int, single_failure: bool, drop_msg: bool,
                 drop_prob: float, total_ticks: int, seed: int,
                 fail_ticks: Optional[Sequence[int]] = None,
                 outdir: str = ".") -> int:
    """Run one scenario on the native engine; writes the three logs."""
    lib = _require_lib()
    ft = None
    arr = None
    if fail_ticks is not None:
        arr = np.ascontiguousarray(fail_ticks, np.int32)
        if arr.shape != (n,):
            raise ValueError(f"fail_ticks must have shape ({n},), "
                             f"got {arr.shape}")
        ft = arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    return lib.gp_run_scenario(n, int(single_failure), int(drop_msg),
                               drop_prob, total_ticks, seed, ft,
                               outdir.encode())


def run_scenario_churn(n: int, single_failure: bool, drop_msg: bool,
                       drop_prob: float, total_ticks: int, seed: int,
                       fail_ticks: Optional[Sequence[int]] = None,
                       rejoin_ticks: Optional[Sequence[int]] = None,
                       outdir: str = ".") -> int:
    """Churn variant: failed peers are wiped at their rejoin tick and
    re-enter through the normal JOINREQ path (Schedule.rejoin_tick's
    native twin)."""
    lib = _require_lib()

    def _ptr(ticks, name):
        if ticks is None:
            return None, None
        arr = np.ascontiguousarray(ticks, np.int32)
        if arr.shape != (n,):
            raise ValueError(f"{name} must have shape ({n},), got {arr.shape}")
        return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), arr

    ft, keep1 = _ptr(fail_ticks, "fail_ticks")
    rt, keep2 = _ptr(rejoin_ticks, "rejoin_ticks")
    if keep1 is not None and keep2 is not None:
        bad = (keep2 != np.iinfo(np.int32).max) & (keep2 <= keep1)
        if bad.any():
            # same rule the JAX schedule enforces (state.py): a rejoin
            # at or before the fail tick collapses the failed window
            raise ValueError(
                f"rejoin_ticks must be > fail_ticks (violated at peers "
                f"{np.flatnonzero(bad).tolist()})")
    return lib.gp_run_scenario_churn(n, int(single_failure), int(drop_msg),
                                     drop_prob, total_ticks, seed, ft, rt,
                                     outdir.encode())


def run_conf(conf_path: str, seed: int = 0, outdir: str = ".") -> int:
    return _require_lib().gp_run_conf(conf_path.encode(), seed,
                                      outdir.encode())


def hash_uniform(seed: int, a: int, b: int, c: int, d: int) -> float:
    return _require_lib().gp_hash_uniform(seed, a, b, c, d)


class NativeBus:
    """Python handle on the EmulNet-shaped native bus (plugin boundary).

    Mirrors the ENinit/ENsend/ENrecv/ENcleanup surface so harnesses (and
    tests) can drive the communication backend directly, as the reference
    driver drives EmulNet.
    """

    def __init__(self, max_nodes: int, total_ticks: int,
                 max_inflight: int = 30000, max_msg_size: int = 4000,
                 drop_prob: float = 0.0, seed: int = 0):
        self._lib = _require_lib()
        self._bus = self._lib.gp_bus_create(max_nodes, total_ticks,
                                            max_inflight, max_msg_size,
                                            drop_prob, seed)
        self.max_nodes = max_nodes
        self.total_ticks = total_ticks

    def close(self):
        if self._bus:
            self._lib.gp_bus_destroy(self._bus)
            self._bus = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def init(self) -> int:
        """ENinit: register the next peer; returns its 0-based index."""
        return self._lib.gp_bus_init(self._bus)

    def send(self, frm: int, to: int, payload: bytes, tick: int,
             drop_active: bool = False, channel: int = 0) -> bool:
        """ENsend: returns True iff enqueued (False = silently dropped)."""
        return bool(self._lib.gp_bus_send(self._bus, frm, to, payload,
                                          len(payload), tick,
                                          int(drop_active), channel))

    def recv(self, me: int, tick: int, chunk_msgs: int = 4096,
             chunk_bytes: int = 1 << 20) -> list[bytes]:
        """ENrecv: drain this peer's queued messages, in send order.

        Consumes in bounded chunks and loops until the queue is empty —
        a message larger than chunk_bytes raises instead of being lost
        (the C side leaves unfitting messages queued).
        """
        buf = ctypes.create_string_buffer(chunk_bytes)
        sizes = (ctypes.c_int * chunk_msgs)()
        more = ctypes.c_int(1)
        out = []
        while more.value:
            cnt = self._lib.gp_bus_recv(self._bus, me, tick, buf, chunk_bytes,
                                        sizes, chunk_msgs,
                                        ctypes.byref(more))
            if cnt == 0 and more.value:
                raise ValueError(
                    f"queued message exceeds chunk_bytes={chunk_bytes}")
            off = 0
            for k in range(cnt):
                out.append(buf.raw[off:off + sizes[k]])
                off += sizes[k]
        return out

    @property
    def inflight(self) -> int:
        return self._lib.gp_bus_inflight(self._bus)

    def cleanup(self, outdir: str = ".") -> bool:
        """ENcleanup: dump msgcount.log."""
        return bool(self._lib.gp_bus_cleanup(self._bus, outdir.encode()))

    def counters(self) -> tuple[np.ndarray, np.ndarray]:
        """(sent, recv) as (max_nodes, total_ticks) uint32 matrices."""
        sent = np.zeros((self.max_nodes, self.total_ticks), np.uint32)
        recv = np.zeros((self.max_nodes, self.total_ticks), np.uint32)
        self._lib.gp_bus_counters(
            self._bus, sent.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            recv.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
        return sent, recv
