"""Peer addressing.

The reference represents a peer address as 6 raw bytes — a little-endian
int32 id plus an int16 port (Member.h:29-55) — assigned sequentially from
1 by ``EmulNet::ENinit`` (EmulNet.cpp:72-77) with port forced to 0.  The
log grammar prints addresses byte-wise as ``b0.b1.b2.b3:port``
(Log.cpp:73).

In the TPU framework a peer *is* its index ``i`` (0-based) into the state
tensors; the wire/log identity ``id = i + 1`` exists only at the
observability boundary.  These helpers convert between the two.
"""

from __future__ import annotations


def peer_id(index: int) -> int:
    """0-based tensor index -> reference peer id (EmulNet.cpp:74)."""
    return index + 1


def peer_index(pid: int) -> int:
    """Reference peer id -> 0-based tensor index."""
    return pid - 1


def addr_str(index: int, port: int = 0) -> str:
    """Dotted log form of a peer address, e.g. index 0 -> ``"1.0.0.0:0"``.

    Matches ``sprintf("%d.%d.%d.%d:%d", ...)`` over the little-endian id
    bytes (Log.cpp:73, Log.cpp:118).
    """
    pid = peer_id(index)
    b = [(pid >> (8 * k)) & 0xFF for k in range(4)]
    return f"{b[0]}.{b[1]}.{b[2]}.{b[3]}:{port}"


def parse_addr(s: str) -> int:
    """Dotted log form -> 0-based peer index (inverse of :func:`addr_str`)."""
    dotted, _, _port = s.partition(":")
    b = [int(x) for x in dotted.split(".")]
    pid = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
    return peer_index(pid)


def display_addr(index: int, port: int = 0) -> str:
    """``Address::getAddress()`` form, e.g. ``"1:0"`` (Member.h:46-52).

    Used by the driver's per-node stdout line (Application.cpp:146).
    """
    return f"{peer_id(index)}:{port}"
