"""Command-line entry point, CLI-compatible with the reference binary.

The reference is invoked as ``./Application <testcase.conf>``
(Application.cpp:27-42) and writes dbg.log / stats.log / msgcount.log
into the working directory.  This module does the same:

    python -m gossip_protocol_tpu testcases/singlefailure.conf

plus framework extras (--seed, --outdir, -n to scale the peer count,
--bench).  The standalone C++ launcher ``native/gossip_app.cc`` embeds
the interpreter and calls :func:`main`, giving a drop-in
``./Application`` binary for harnesses that exec a native executable.
"""

from __future__ import annotations

import argparse
import json
import sys

from .addressing import display_addr
from .config import SimConfig


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="gossip_protocol_tpu",
        description="TPU-native gossip membership-protocol simulator")
    ap.add_argument("conf", help="testcase .conf file (reference format)")
    ap.add_argument("--seed", type=int, default=None,
                    help="PRNG seed (default: from config; reference uses "
                         "wall-clock seeding, pass --seed -1 to mimic)")
    ap.add_argument("-n", "--peers", type=int, default=None,
                    help="override MAX_NNB (scale the scenario)")
    ap.add_argument("--ticks", type=int, default=None,
                    help="override TOTAL_RUNNING_TIME (default 700)")
    ap.add_argument("--outdir", default=".",
                    help="directory for dbg.log/stats.log/msgcount.log")
    ap.add_argument("--bench", action="store_true",
                    help="benchmark mode: no logs, print one JSON line")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the per-node introduction stdout lines")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. cpu, tpu); default: "
                         "jax's own selection")
    ap.add_argument("--model", default=None, choices=["full_view", "overlay"],
                    help="protocol family: full_view (reference-faithful, "
                         "dbg.log output) or overlay (bounded partial-view "
                         "for large N; prints one summary-metrics JSON line)")
    ap.add_argument("--topology", default=None,
                    choices=["uniform", "powerlaw"],
                    help="overlay exchange-degree family (uniform fanout "
                         "or scale-free Pareto out-degrees)")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    overrides = {}
    if args.seed is not None:
        overrides["seed"] = args.seed if args.seed >= 0 else None
        if overrides["seed"] is None:
            import time as _t
            overrides["seed"] = int(_t.time())
    if args.peers is not None:
        overrides["max_nnb"] = args.peers
    if args.ticks is not None:
        overrides["total_ticks"] = args.ticks
    if args.model is not None:
        overrides["model"] = args.model
    if args.topology is not None:
        overrides["topology"] = args.topology
    try:
        cfg = SimConfig.from_conf(args.conf, **overrides)
    except (OSError, ValueError) as e:
        # clean diagnostic + the native launcher's conf-error exit code
        # (gossip_app.cc), instead of a raw traceback
        print(f"gossip_protocol_tpu: {e}", file=sys.stderr)
        return 2

    if cfg.model == "overlay":
        # the overlay reports scalar metrics, not per-event logs
        # (events at 65k+ cannot be dense masks; models/overlay.py)
        import numpy as np

        from .models.overlay import OverlaySimulation
        res = OverlaySimulation(cfg).run()
        m = res.metrics
        uncovered, victims_left = res.final_coverage()
        print(json.dumps({
            "n": cfg.n, "ticks": cfg.total_ticks,
            "wall_s": round(res.wall_seconds, 6),
            "node_ticks_per_s": round(res.node_ticks_per_second, 1),
            "in_group_final": int(np.asarray(m.in_group)[-1]),
            "victim_slots_final": int(np.asarray(m.victim_slots)[-1]),
            "live_uncovered_final": uncovered,
            "victim_entries_final": victims_left,
            "removals_total": int(np.asarray(m.removals).sum()),
        }))
        return 0

    from .core.sim import Simulation

    sim = Simulation(cfg)
    if args.bench:
        res = sim.run_bench()
        print(json.dumps({
            "n": cfg.n, "ticks": cfg.total_ticks,
            "wall_s": round(res.wall_seconds, 6),
            "ticks_per_s": round(res.ticks_per_second, 1),
            "node_ticks_per_s": round(res.node_ticks_per_second, 1),
        }))
        return 0

    if not args.quiet:
        # parity with the driver's stdout (Application.cpp:146) — the
        # reference prints these as each node is introduced
        for i in range(cfg.n):
            print(f"{i}-th introduced node is assigned with the address: "
                  f"{display_addr(i)}")

    res = sim.run()
    res.write_logs(args.outdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
