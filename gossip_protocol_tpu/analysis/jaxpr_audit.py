"""Jaxpr auditor: structural rules over the registered hot programs.

The invariants this pass enforces are *lowering-shaped*: they are
invisible in the Python source (the code says ``lax.cond`` either
way) and only appear — or silently disappear — in the traced program.
``jax.make_jaxpr`` of each registered hot program is walked
recursively (into scan bodies, cond branches, pjit/shard_map inner
jaxprs, and Pallas kernel jaxprs) and checked against named rules:

``cond-stays-cond``
    The windowed draws (the drop/partition window cond in
    ops/drop.py, the overlay's SLOT_EPOCH re-slot cond) must lower to
    REAL ``cond`` primitives.  Batching their predicate — a batched
    clock, a per-lane drop plane — silently degrades them to
    both-branches ``select_n``: the draw then runs on EVERY tick
    (measured +43% wall for the re-slot, 2.6x the whole dense tick
    for the drop draw — PERF §8/§9/§10).  Programs with a "batched
    twin" (the fleet's SCHED_AXES_BATCHED build) are checked by
    comparison — the shared-plane build must carry strictly more
    conds; programs without a twin are checked against a minimum
    cond count.  This generalizes (and now backs) the jaxpr string
    grep that pinned the mesh drop plane in tests/test_fleet_mesh.py.

``zero-collectives-per-tick``
    No psum / all_gather / all_to_all / ppermute / reduce_scatter
    anywhere in the lane-mesh programs (and none in the single-device
    programs either, where they would be plain bugs).  Lane sharding
    is zero-collective data parallelism by design (PERF §10); one
    accidental cross-lane reduction turns every tick into a
    synchronization point.

``donation-taken``
    Programs built with a donated scan carry (``donate_argnums``)
    must actually alias that input to an output — primary evidence is
    ``input_output_alias`` in the compiled executable, which both the
    single-device and sharded paths carry (shard_map plumbs donation
    at compile time with no MLIR marker; verified on jax 0.4.37 +
    XLA:CPU), with the jax-version-fragile MLIR
    ``tf.aliasing_output`` marker demoted to fallback.  A donation
    that quietly
    stops lowering (a dtype change, a broken alias) doubles the
    resident state and — worse — changes the deletion semantics the
    PendingFleet donation-hold protocol depends on (PERF §11).

``no-transfer-in-scan``
    No ``device_put`` / host-callback primitives inside the hot
    programs.  A transfer inside the scanned body serializes every
    tick on the host (the PERF §11 bug class, found by
    instrumentation in PR 6).

Programs are registered in :data:`PROGRAMS` with their provenance;
each entry traces tiny configs (n=16 dense / n=64 overlay) so the
audit stays test-tier fast.  Mesh programs need >= 2 devices — under
``python -m gossip_protocol_tpu.analysis`` virtual CPU devices are
forced before jax imports (__main__.py), mirroring tests/conftest.py;
when fewer devices are live those entries are skipped with a notice
rather than silently passing.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Optional

from . import Finding

#: cross-device collective primitives (by jaxpr primitive name) that
#: must never appear in a lane-parallel tick body
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_gather_invariant", "all_to_all",
    "reduce_scatter", "pgather", "axis_all_gather",
})

#: transfer / host-callback primitives that must never appear inside
#: a hot program (the scanned body especially)
TRANSFER_PRIMS = frozenset({
    "device_put", "copy_to_host", "pure_callback", "io_callback",
    "debug_callback", "callback", "outside_call", "host_callback_call",
    "infeed", "outfeed",
})


# ---- the jaxpr walker ------------------------------------------------
def _sub_jaxprs(param_value):
    """Sub-jaxprs hiding in one eqn param value (ClosedJaxpr, Jaxpr,
    or a list/tuple of either — cond branches, scan/pjit bodies,
    shard_map inner jaxprs, Pallas kernel jaxprs)."""
    vals = param_value if isinstance(param_value, (list, tuple)) \
        else (param_value,)
    out = []
    for v in vals:
        if hasattr(v, "jaxpr"):         # ClosedJaxpr
            out.append(v.jaxpr)
        elif hasattr(v, "eqns"):        # raw Jaxpr
            out.append(v)
    return out


def iter_eqns(jaxpr, path=()):
    """Yield ``(path, eqn)`` for every equation, recursing into every
    nested jaxpr (scan/cond/pjit/shard_map/pallas_call/custom_* —
    anything that parks a Jaxpr in its params).  ``path`` is the
    chain of enclosing primitives, e.g.
    ``('pjit.jaxpr', 'scan.jaxpr', 'cond.branches')``."""
    for eqn in jaxpr.eqns:
        yield path, eqn
        for k, v in eqn.params.items():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(
                    sub, path + (f"{eqn.primitive.name}.{k}",))


def prim_counts(closed_jaxpr) -> dict:
    """Primitive-name histogram over the whole nested program."""
    counts: dict = {}
    for _, eqn in iter_eqns(closed_jaxpr.jaxpr):
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
    return counts


def find_prims(closed_jaxpr, names) -> list[tuple[str, str]]:
    """``(path, primitive)`` of every occurrence of ``names``."""
    hits = []
    for path, eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in names:
            hits.append(("/".join(path) or "<top>", eqn.primitive.name))
    return hits


# ---- program registry ------------------------------------------------
@dataclass
class AuditedProgram:
    """One registered hot program, traced and ready to check.

    ``jaxpr`` is the traced program; ``twin`` (optional) is the
    batched-plane build of the same program for the comparison form
    of cond-stays-cond; ``min_cond`` the floor for the absolute
    form."""

    name: str
    provenance: str
    jaxpr: object
    rules: tuple
    twin: object = None
    min_cond: int = 0
    #: ``jax.stages.Lowered`` of the program when it declares a
    #: donated carry (None otherwise).  The rule compiles it and reads
    #: the executable's ``input_output_alias`` — the authoritative
    #: record on every path (single-device AND shard_map; verified on
    #: jax 0.4.37 + XLA:CPU) — keeping the pre-compile MLIR
    #: ``tf.aliasing_output`` marker only as a version-drift fallback.
    lowered: object = None
    #: :class:`..sharding_flow.ShardingContract` for mesh programs the
    #: sharding-flow pass certifies (None = pass skips the program).
    contract: object = None
    notes: str = ""


def _provenance(fn) -> str:
    try:
        f = inspect.unwrap(fn)
        file = inspect.getsourcefile(f)
        _, line = inspect.getsourcelines(f)
        import os
        rel = os.path.relpath(file, os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        return f"{rel}:{line}"
    except (TypeError, OSError):
        return repr(fn)


def _dense_cfg():
    from ..config import SimConfig
    return SimConfig(max_nnb=16, total_ticks=30, drop_msg=True,
                     msg_drop_prob=0.1, single_failure=True)


def _overlay_cfg():
    from ..config import SimConfig
    return SimConfig(model="overlay", max_nnb=64, total_ticks=96,
                     churn_rate=0.2, rejoin_after=None, seed=1,
                     step_rate=4.0 / 64)


def _dense_fleet_args(cfg, shared: bool):
    from ..core.fleet import _stack_scheds, _stack_states
    from ..state import init_state, make_schedule
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    scheds = [make_schedule(c) for c in cfgs]
    states = _stack_states([init_state(c) for c in cfgs])
    return states, _stack_scheds(scheds, shared)


def _overlay_fleet_args(cfg):
    from ..core.fleet import stack_lanes
    from ..models.overlay import init_overlay_state, make_overlay_schedule
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    states = stack_lanes([init_overlay_state(c) for c in cfgs])
    states = states.replace(tick=init_overlay_state(cfgs[0]).tick)
    scheds = stack_lanes([make_overlay_schedule(c) for c in cfgs])
    return states, scheds


def build_programs(mesh_devices: int = 2) -> list[AuditedProgram]:
    """Trace the registered hot programs (tiny configs).

    Covers the acceptance surface: solo tick (dense + overlay), fleet
    scan (dense shared-vs-batched twin + overlay), the D=2 lane-mesh
    ``shard_map`` program (dense twin pair + overlay), the 2-D
    lanes×peers prototype (2×4 devices, sharding-contract-carrying),
    the grid kernel, and the checkpoint-leg resume program.
    """
    import jax

    from ..core.fleet import FleetSimulation
    from ..core.tick import make_run
    from ..models.overlay import (init_overlay_state, make_overlay_run,
                                  make_overlay_fleet_run,
                                  make_overlay_schedule)
    from ..models.overlay_grid import make_grid_run
    from ..models.segments import checkpoint_ticks
    from ..state import init_state, make_schedule

    progs: list[AuditedProgram] = []

    # ---- solo dense trace (drop config: the ops/drop.py cond) -----
    dcfg = _dense_cfg()
    run = make_run(dcfg, with_events=True, use_pallas=False)
    jx = jax.make_jaxpr(run)(init_state(dcfg), make_schedule(dcfg))
    progs.append(AuditedProgram(
        name="solo-dense-trace", provenance=_provenance(make_run),
        jaxpr=jx, min_cond=1,
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "no-transfer-in-scan")))

    # ---- solo overlay (SLOT_EPOCH re-slot cond) --------------------
    ocfg = _overlay_cfg()
    orun = make_overlay_run(ocfg, use_pallas=False)
    ojx = jax.make_jaxpr(orun)(init_overlay_state(ocfg),
                               make_overlay_schedule(ocfg))
    progs.append(AuditedProgram(
        name="solo-overlay", provenance=_provenance(make_overlay_run),
        jaxpr=ojx, min_cond=1,
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "no-transfer-in-scan")))

    # ---- fleet dense bench: shared-drop build vs batched twin ------
    fs = FleetSimulation(dcfg)
    dargs = _dense_fleet_args(dcfg, True)
    dargs_b = _dense_fleet_args(dcfg, False)
    frun = fs._dense_bench_fn(2, dcfg.n, True)
    fjx = jax.make_jaxpr(frun)(*dargs)
    ftwin = jax.make_jaxpr(fs._dense_bench_fn(2, dcfg.n, False))(
        *dargs_b)
    flow = frun.lower(*dargs)
    progs.append(AuditedProgram(
        name="fleet-dense-bench",
        provenance=_provenance(FleetSimulation._dense_bench_fn),
        jaxpr=fjx, twin=ftwin, min_cond=1, lowered=flow,
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "donation-taken", "no-transfer-in-scan")))

    # ---- canonical fleet (PR 16: pad-ladder + quantized window) ----
    # The equivalence-class program: non-power-of-two members padded
    # to the rung, the SHARED quantized superset drop window riding
    # unbatched (SCHED_AXES_CANON).  The twin batches the drop plane —
    # the shared build must keep strictly more real conds, proving
    # the quantized window did not degrade the drop cond to select_n
    # and the world operands stayed traced data (zero extra bakes).
    import numpy as np

    from ..core.fleet import CanonicalFleetSimulation, _stack_scheds
    from ..state import make_schedule_host, pad_schedule_host
    ncfg = dcfg.replace(max_nnb=10)
    cs = CanonicalFleetSimulation(ncfg)
    ccfgs = [ncfg.replace(seed=s) for s in (1, 2)]
    cscheds = [pad_schedule_host(make_schedule_host(c), cs.rung)
               for c in ccfgs]
    cstates = cs._dense_init_stacked(cs.cfg, 2)(
        np.asarray([c.seed for c in ccfgs], np.int64))
    cargs = (cstates, cs._stack_scheds_canon(cscheds))
    cargs_b = (cstates, _stack_scheds(cscheds, False))
    ncrun = cs._canon_run_builder(ncfg.total_ticks)
    ncjx = jax.make_jaxpr(ncrun)(*cargs)
    nctwin = jax.make_jaxpr(
        cs._canon_run_builder(ncfg.total_ticks, batched_drop=True))(
        *cargs_b)
    nclow = jax.jit(ncrun, donate_argnums=(0,)).lower(*cargs)
    progs.append(AuditedProgram(
        name="fleet-dense-canonical",
        provenance=_provenance(
            CanonicalFleetSimulation._canon_run_builder),
        jaxpr=ncjx, twin=nctwin, min_cond=1, lowered=nclow,
        notes=f"n={ncfg.n} padded to rung {cs.rung}; shared "
              "quantized window vs batched-drop twin",
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "donation-taken", "no-transfer-in-scan")))

    # ---- fleet overlay (vmap with the shared clock) ----------------
    ofrun = make_overlay_fleet_run(ocfg, 2, use_pallas=False)
    ofargs = _overlay_fleet_args(ocfg)
    ofjx = jax.make_jaxpr(ofrun)(*ofargs)
    oflow = ofrun.lower(*ofargs)
    progs.append(AuditedProgram(
        name="fleet-overlay",
        provenance=_provenance(make_overlay_fleet_run),
        jaxpr=ofjx, min_cond=1, lowered=oflow,
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "donation-taken", "no-transfer-in-scan")))

    # ---- checkpoint-leg resume program (a cut-to-cut scan) ---------
    cuts = checkpoint_ticks(ocfg)
    if cuts:
        start = cuts[0]
        length = (cuts[1] - start) if len(cuts) > 1 \
            else ocfg.total_ticks - start
        lrun = make_overlay_fleet_run(ocfg, 2, length=length,
                                      start_tick=start,
                                      use_pallas=False)
        # the XLA leg path reads the clock from the carried state, so
        # tracing with the tick-0 carry is exact (the value is a
        # traced arg, not baked)
        ljx = jax.make_jaxpr(lrun)(*ofargs)
        progs.append(AuditedProgram(
            name="fleet-overlay-leg",
            provenance=_provenance(make_overlay_fleet_run),
            jaxpr=ljx, min_cond=1,
            notes=f"leg [{start}, {start + length}) of "
                  f"{ocfg.total_ticks}",
            rules=("cond-stays-cond", "zero-collectives-per-tick",
                   "no-transfer-in-scan")))

    # ---- grid kernel (interpret off-TPU; pl.when lowers to cond) ---
    gcfg = _overlay_cfg().replace(churn_rate=0.0, seed=3)
    grun = make_grid_run(gcfg, 32, start_tick=None)
    gjx = jax.make_jaxpr(grun)(init_overlay_state(gcfg),
                               make_overlay_schedule(gcfg))
    progs.append(AuditedProgram(
        name="grid-kernel", provenance=_provenance(make_grid_run),
        jaxpr=gjx, min_cond=1,
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "no-transfer-in-scan")))

    # ---- composed-world programs (round 2: several planes layered) -
    # The composed worlds are sweep hot programs now
    # (models/scenarios.py dense_composed_* / overlay_composed_*):
    # audit the exact traced form FleetService compiles — the forged
    # byz planes and the message-age latency dimension must neither
    # break cond structure nor smuggle per-tick collectives or
    # transfers into the scan body.
    ccfg = dcfg.replace(byz_rate=0.2, byz_boost=8, link_latency=3,
                        flap_rate=0.3, flap_period=12, flap_down=4,
                        partition_groups=2, partition_open_tick=8,
                        partition_close_tick=16)
    crun = make_run(ccfg, with_events=True, use_pallas=False)
    cjx = jax.make_jaxpr(crun)(init_state(ccfg), make_schedule(ccfg))
    progs.append(AuditedProgram(
        name="solo-dense-composed", provenance=_provenance(make_run),
        jaxpr=cjx, min_cond=1,
        notes="byz + latency + flap + partition on the drop config",
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "no-transfer-in-scan")))

    occfg = ocfg.replace(byz_rate=0.15, byz_boost=8, link_latency=3)
    ocrun = make_overlay_run(occfg, use_pallas=False)
    ocjx = jax.make_jaxpr(ocrun)(init_overlay_state(occfg),
                                 make_overlay_schedule(occfg))
    progs.append(AuditedProgram(
        name="solo-overlay-composed",
        provenance=_provenance(make_overlay_run),
        jaxpr=ocjx, min_cond=1,
        notes="byz + latency over the churn script (send-history "
              "shift register rides the scan carry)",
        rules=("cond-stays-cond", "zero-collectives-per-tick",
               "no-transfer-in-scan")))

    # ---- lane-mesh programs (D=2) ----------------------------------
    import jax as _jax

    from ..core.fleet import SCHED_AXES_SHARED_DROP, WORLD_AXES
    from ..models.overlay import (OVERLAY_FLEET_STATE_AXES,
                                  OverlaySchedule)
    # sharding_flow imports this module; import lazily to break the
    # cycle.  Each mesh entry's contract carries independently derived
    # expected in_names so spec-derivation-consistent can cross-check
    # the builders' own spec derivation.
    from .sharding_flow import (ShardingContract, all_batched_dims,
                                axes_tree_dims)

    if _jax.device_count() >= mesh_devices:
        from ..parallel.fleet_mesh import (MeshFleetSimulation,
                                           make_lane_mesh)
        mesh = make_lane_mesh(mesh_devices)
        ms = MeshFleetSimulation(dcfg, mesh)
        mrun = ms._dense_bench_fn(2, dcfg.n, True)
        mjx = jax.make_jaxpr(mrun.jitted)(*dargs)
        mtwin = jax.make_jaxpr(ms._dense_bench_fn(2, dcfg.n, False)
                               .jitted)(*dargs_b)
        mlow = mrun.jitted.lower(*dargs)
        mdims = (axes_tree_dims("state", WORLD_AXES)
                 + axes_tree_dims("sched", SCHED_AXES_SHARED_DROP))
        progs.append(AuditedProgram(
            name=f"mesh-dense-bench-d{mesh_devices}",
            provenance=_provenance(MeshFleetSimulation._dense_bench_fn),
            jaxpr=mjx, twin=mtwin, min_cond=1, lowered=mlow,
            contract=ShardingContract(
                mesh_axes=("lanes",),
                zero_collective_axes=("lanes",),
                replicated_plane=tuple(n for n, d in mdims if not d),
                expected_in_names=mdims),
            rules=("cond-stays-cond", "zero-collectives-per-tick",
                   "donation-taken", "no-transfer-in-scan")))

        mos = MeshFleetSimulation(ocfg, mesh)
        morun = mos._overlay_fleet_fn(2)
        mojx = jax.make_jaxpr(morun.jitted)(*ofargs)
        molow = morun.jitted.lower(*ofargs)
        modims = (axes_tree_dims("state", OVERLAY_FLEET_STATE_AXES)
                  + all_batched_dims("sched", OverlaySchedule))
        progs.append(AuditedProgram(
            name=f"mesh-overlay-d{mesh_devices}",
            provenance=_provenance(
                MeshFleetSimulation._overlay_fleet_fn),
            jaxpr=mojx, min_cond=1, lowered=molow,
            contract=ShardingContract(
                mesh_axes=("lanes",),
                zero_collective_axes=("lanes",),
                replicated_plane=tuple(n for n, d in modims if not d),
                expected_in_names=modims),
            rules=("cond-stays-cond", "zero-collectives-per-tick",
                   "donation-taken", "no-transfer-in-scan")))
    else:
        progs.append(AuditedProgram(
            name=f"mesh-(skipped: {_jax.device_count()} device(s) "
                 f"live, need {mesh_devices})",
            provenance="parallel/fleet_mesh.py", jaxpr=None, rules=(),
            notes="force virtual devices: XLA_FLAGS="
                  "--xla_force_host_platform_device_count=8 before "
                  "jax imports (python -m gossip_protocol_tpu."
                  "analysis does this itself)"))

    # ---- 2-D lanes x peers prototype (2 x 4 = 8 devices) -----------
    # The flagship sharding-flow entry: the ROADMAP's 2-D mesh,
    # registered BEFORE the serving wiring lands so the per-axis
    # rules gate that PR (ISSUE 14).  zero-collectives-per-tick is
    # deliberately NOT on this program — its peer axis legitimately
    # collects every tick; the axis-aware contract replaces it.
    n2_lanes, n2_peers = 2, 4
    if _jax.device_count() >= n2_lanes * n2_peers:
        from ..parallel.fleet_mesh import (
            LANE_PEER_TICK_COLLECTIVE_BUDGET, make_lane_peer_bench_fn,
            make_lane_peer_mesh)
        from ..parallel.sharded import PEER_AXIS, peer_spec_trees
        mesh2 = make_lane_peer_mesh(n2_lanes, n2_peers)
        prun = make_lane_peer_bench_fn(dcfg, mesh2)
        pjx = jax.make_jaxpr(prun)(*dargs)
        plow = prun.lower(*dargs)
        peer_state, peer_sched = peer_spec_trees(PEER_AXIS)
        pdims = (axes_tree_dims("state", WORLD_AXES,
                                peer_specs=peer_state)
                 + axes_tree_dims("sched", SCHED_AXES_SHARED_DROP,
                                  peer_specs=peer_sched))
        progs.append(AuditedProgram(
            name="mesh2d-lanes-peers",
            provenance=_provenance(make_lane_peer_bench_fn),
            jaxpr=pjx, min_cond=1, lowered=plow,
            contract=ShardingContract(
                mesh_axes=("lanes", PEER_AXIS),
                zero_collective_axes=("lanes",),
                budgets={PEER_AXIS: LANE_PEER_TICK_COLLECTIVE_BUDGET},
                replicated_plane=tuple(n for n, d in pdims if not d),
                expected_in_names=pdims),
            rules=("cond-stays-cond", "donation-taken",
                   "no-transfer-in-scan"),
            notes=f"{n2_lanes} lanes x {n2_peers} peers on virtual "
                  "CPU devices (the ROADMAP 2-D prototype; "
                  "bit-identical to the 1-D fleet — "
                  "tests/test_fleet_mesh.py)"))

        # the PRODUCTION 2-D serving program (PR 19): the same
        # composition built by MeshFleetSimulation itself — what
        # FleetService(mesh=Mesh((lanes, peers))) actually dispatches
        # for a peer-divisible dense bucket.  Held to the identical
        # per-axis contract as the prototype registration above: the
        # lane axis moves zero bytes, the peer axis stays within its
        # 5-collective tick budget, and the replicated plane is
        # exactly the unbatched set.
        from ..parallel.fleet_mesh import MeshFleetSimulation as _MFS
        ms2 = _MFS(dcfg, mesh2)
        srun = ms2._dense_bench_fn(2, dcfg.n, True)
        sjx = jax.make_jaxpr(srun.jitted)(*dargs)
        slow = srun.jitted.lower(*dargs)
        progs.append(AuditedProgram(
            name="mesh2d-serving",
            provenance=_provenance(_MFS._dense_bench_fn),
            jaxpr=sjx, min_cond=1, lowered=slow,
            contract=ShardingContract(
                mesh_axes=("lanes", PEER_AXIS),
                zero_collective_axes=("lanes",),
                budgets={PEER_AXIS: LANE_PEER_TICK_COLLECTIVE_BUDGET},
                replicated_plane=tuple(n for n, d in pdims if not d),
                expected_in_names=pdims),
            rules=("cond-stays-cond", "donation-taken",
                   "no-transfer-in-scan"),
            notes=f"the production serving path ({n2_lanes} lanes x "
                  f"{n2_peers} peers, n={dcfg.n} peer-sharded): "
                  "MeshFleetSimulation._dense_bench_fn with _peer_comm "
                  "— FleetService(mesh=) dispatches this program"))
    else:
        progs.append(AuditedProgram(
            name=f"mesh2d-(skipped: {_jax.device_count()} device(s) "
                 f"live, need {n2_lanes * n2_peers})",
            provenance="parallel/fleet_mesh.py", jaxpr=None, rules=(),
            notes="force virtual devices: XLA_FLAGS="
                  "--xla_force_host_platform_device_count=8 before "
                  "jax imports (python -m gossip_protocol_tpu."
                  "analysis does this itself)"))
    return progs


# ---- the rules -------------------------------------------------------
def check_cond_stays_cond(prog: AuditedProgram) -> list[Finding]:
    """Comparison form when a batched twin exists (the shared-plane
    build must lower strictly more real conds than the batched one),
    absolute form otherwise (>= min_cond conds present)."""
    out = []
    n_cond = prim_counts(prog.jaxpr).get("cond", 0)
    if prog.twin is not None:
        n_twin = prim_counts(prog.twin).get("cond", 0)
        if not n_cond > n_twin:
            out.append(Finding(
                "cond-stays-cond", prog.name,
                f"shared-plane program lowers {n_cond} cond(s) vs "
                f"{n_twin} in the batched twin — the shared drop/"
                "window plane no longer keeps its lax.cond a real "
                "cond (the draw runs every tick as a both-branches "
                "select; PERF §9/§10)",
                path=prog.provenance))
    if n_cond < prog.min_cond:
        out.append(Finding(
            "cond-stays-cond", prog.name,
            f"expected >= {prog.min_cond} real cond primitive(s), "
            f"found {n_cond} — a clock-/window-derived cond degraded "
            "to a both-branches select_n (batched clock or batched "
            "plane; PERF §8)",
            path=prog.provenance))
    return out


def check_zero_collectives(prog: AuditedProgram) -> list[Finding]:
    hits = find_prims(prog.jaxpr, COLLECTIVE_PRIMS)
    return [Finding(
        "zero-collectives-per-tick", prog.name,
        f"collective primitive {name!r} in the tick program — lane "
        "parallelism must move zero bytes between devices (PERF §10)",
        path=p) for p, name in hits]


def check_donation_taken(prog: AuditedProgram) -> list[Finding]:
    if prog.lowered is None:
        return []
    # the compiled executable's input_output_alias is the primary
    # evidence on EVERY path: single-device donation carries it too,
    # and the sharded path (shard_map under jit) carries ONLY it —
    # donation there is plumbed at compile time with no MLIR marker.
    # The MLIR tf.aliasing_output arg attr is a TF-flavored spelling
    # that jax versions have moved around; keep it as fallback only.
    if "input_output_alias" in prog.lowered.compile().as_text():
        return []
    if "tf.aliasing_output" in prog.lowered.as_text():
        return []
    return [Finding(
        "donation-taken", prog.name,
        "program declares a donated carry (donate_argnums) but "
        "neither the lowering nor the compiled executable aliases "
        "an input to an output — donation silently dropped (doubles "
        "resident state and breaks the PendingFleet donation-hold "
        "timing, PERF §11)",
        path=prog.provenance)]


def check_no_transfer(prog: AuditedProgram) -> list[Finding]:
    hits = find_prims(prog.jaxpr, TRANSFER_PRIMS)
    return [Finding(
        "no-transfer-in-scan", prog.name,
        f"transfer/callback primitive {name!r} inside the hot "
        "program — every occurrence serializes the device on the "
        "host (PERF §11's silent-serializer class)",
        path=p) for p, name in hits]


_RULE_FNS = {
    "cond-stays-cond": check_cond_stays_cond,
    "zero-collectives-per-tick": check_zero_collectives,
    "donation-taken": check_donation_taken,
    "no-transfer-in-scan": check_no_transfer,
}


def audit_program(prog: AuditedProgram, rules=None) -> list[Finding]:
    """Apply the program's registered rules (optionally restricted)."""
    if prog.jaxpr is None:        # a skipped registry entry
        return []
    out = []
    for r in prog.rules:
        if rules is not None and r not in rules:
            continue
        out += _RULE_FNS[r](prog)
    return out


def audit(rules=None, mesh_devices: int = 2,
          programs=None) -> list[Finding]:
    """Trace the registry and run every applicable rule.

    The traced roster is kept on ``audit.last_programs`` so the CLI
    can show what was covered (and, crucially, what was SKIPPED —
    a mesh entry skipping for want of devices must be visible).
    With a ``rules`` filter selecting NO jaxpr rule, the registry is
    not traced at all (tracing + lowering the 8 programs costs ~8s —
    a single-AST-rule run must not pay it)."""
    if rules is not None and not set(rules) & set(_RULE_FNS):
        audit.last_programs = []
        return []
    progs = build_programs(mesh_devices) if programs is None \
        else programs
    audit.last_programs = progs
    findings = []
    for p in progs:
        findings += audit_program(p, rules=rules)
    return findings


audit.last_programs = []
