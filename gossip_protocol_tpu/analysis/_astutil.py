"""Shared AST plumbing for the static passes (purity_lint,
cache_keys) — one definition each, so a fix to chain resolution can
never silently diverge the two passes."""

from __future__ import annotations

import ast
import os

#: repository root (the directory holding gossip_protocol_tpu/)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def attr_chain(node) -> list[str]:
    """``a.b.c`` -> ['a', 'b', 'c']; [] when the root is not a Name
    (a call result, a subscript — chains the passes cannot reason
    about and deliberately skip)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []
