"""Cache-key completeness: every config field a traced builder reads
must be part of its compile-cache/bucket key — or flow through the
Schedule arrays as data.

The stale-program bug class: ``make_run``/``make_grid_run``/the fleet
builders bake config values into compiled programs, and their cache
keys (core/tick.make_run's key tuple, core/fleet.fleet_shape_key +
models/segments.plan_signature + SimConfig.worlds_key, and the
serving layer's service/bucket.bucket_key on top) must name every
such value.  A field that a builder reads but no key folds in means
two configs differing only in that field can be served ONE compiled
program — wrong results with no error anywhere.  PR 1 introduced the
plan-signature key component for exactly one such edit (a moved
phase boundary); this pass generalizes the check to every SimConfig
field by AST attribute-access scan.

The sound set is::

    fields_read(builders)  ⊆  fields_read(key functions)
                              ∪ fields_read(schedule builders)

because anything the schedule builders read flows into the Schedule
arrays and enters the compiled program as *data* (per-call inputs),
not baked constants.  The overlay tier keys the ENTIRE config
(``fleet_shape_key`` bakes ``cfg.replace(seed=0)``), which this pass
verifies structurally (the replace-marker must still be there) —
that one line is what makes "the overlay compiles most of the config
statically" safe at all.

Reported findings name the missing field and every builder location
that reads it.
"""

from __future__ import annotations

import ast
import dataclasses
import os

from . import Finding
from ._astutil import REPO_ROOT, attr_chain
from ..config import SimConfig

SIM_FIELDS = frozenset(f.name for f in dataclasses.fields(SimConfig))

#: property aliases that read like fields in the scanned source
#: (``cfg.n`` IS ``cfg.max_nnb``, config.py)
FIELD_ALIASES = {"n": "max_nnb"}

#: names a SimConfig rides under in the scanned functions
CFG_ROOTS = frozenset({"cfg", "c", "c0", "cw", "cfg_w", "gcfg",
                       "lane_cfg", "fleet_cfg", "dcfg", "ocfg"})

#: functions whose reads BAKE config into compiled programs
BUILDER_FUNCS = {
    "gossip_protocol_tpu/core/tick.py": (
        "make_run", "make_tick"),
    "gossip_protocol_tpu/core/dense_corner.py": (
        "make_corner_run", "active_bound", "bench_stream_width"),
    "gossip_protocol_tpu/core/dense_mega.py": (
        "dense_mega_supported", "make_dense_mega_run"),
    "gossip_protocol_tpu/core/fleet.py": (
        "_shared_drop", "fleet_shape_key", "_dense_bench_fn",
        "_dense_trace_fn", "launch", "launch_bench", "launch_leg",
        "_overlay_launch", "_overlay_leg_launch",
        "_dense_trace_leg_launch", "_overlay_fleet_fn", "_lane_cfgs",
        "_canon_run_builder", "_stack_scheds_canon",
        "_canon_trace_lanes"),
    "gossip_protocol_tpu/models/overlay.py": (
        "make_overlay_run", "make_overlay_tick",
        "make_overlay_fleet_run"),
    "gossip_protocol_tpu/models/overlay_grid.py": (
        "make_grid_run", "make_grid_fleet_run", "grid_supported",
        "_grid_kern_kwargs", "_step_frac"),
    "gossip_protocol_tpu/models/overlay_mega.py": (
        "mega_supported", "make_mega_run"),
}

#: functions whose reads form the CACHE/BUCKET KEYS
KEY_FUNCS = {
    "gossip_protocol_tpu/core/fleet.py": ("fleet_shape_key",),
    "gossip_protocol_tpu/models/segments.py": (
        "plan_signature", "phase_windows", "step_fraction",
        "checkpoint_ticks"),
    "gossip_protocol_tpu/config.py": ("worlds_key",),
    "gossip_protocol_tpu/service/bucket.py": ("bucket_key",),
    "gossip_protocol_tpu/core/dense_corner.py": ("active_bound",),
}

#: the CANONICAL key tier (PR 16, service/canonical.py): what the
#: equivalence-class key folds in — the pad-ladder rung over n, the
#: quantized plan signature, and the operand-vs-static world split.
#: Kept SEPARATE from KEY_FUNCS: a field only the canonical key reads
#: must not count as covered for the exact-bucket soundness set.
CANON_KEY_FUNCS = {
    "gossip_protocol_tpu/service/canonical.py": (
        "canonical_bucket_key", "canonical_fleet_shape_key",
        "canonical_supported", "ladder_rung", "canonical_drop_window",
        "canonical_drop_active"),
    "gossip_protocol_tpu/models/segments.py": (
        "quantized_plan_signature", "quantize_tick"),
    "gossip_protocol_tpu/worlds.py": ("canonical_world_key",),
}

#: what the canonical program actually BAKES: the shared tick builder
#: plus the canonical fleet's own staging/slicing.  The canonical
#: soundness set is the same shape as the exact one:
#: fields_read(canon builders) ⊆ fields_read(canon keys) ∪ data.
CANON_BUILDER_FUNCS = {
    "gossip_protocol_tpu/core/tick.py": ("make_tick",),
    "gossip_protocol_tpu/core/fleet.py": (
        "_canon_run_builder", "_stack_scheds_canon",
        "_canon_trace_lanes"),
}

#: functions whose reads flow through the Schedule arrays as DATA
DATA_FUNCS = {
    "gossip_protocol_tpu/state.py": (
        "make_schedule_host", "make_schedule", "init_state",
        "slice_schedule", "pad_schedule_host"),
    "gossip_protocol_tpu/models/overlay.py": (
        "make_overlay_schedule", "resolved_dims",
        "degree_thresholds", "init_overlay_state"),
    "gossip_protocol_tpu/config.py": ("start_tick",),
}

#: every function in worlds.py is a schedule-data builder (the hashed
#: node assignments are seed data; the windows are ALSO folded into
#: plan_signature via phase_windows — both directions are covered)
DATA_MODULES = ("gossip_protocol_tpu/worlds.py",)


def _collect_reads(nodes, relfile, roots=CFG_ROOTS,
                   self_cfg=True) -> dict:
    """``{field: [file:line, ...]}`` of SimConfig attribute reads on
    the given roots (plus ``self.<root>`` chains and bare ``self``
    for config methods)."""
    reads: dict = {}
    for node in nodes:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Attribute):
                continue
            chain = attr_chain(sub)
            if chain:
                chain[-1] = FIELD_ALIASES.get(chain[-1], chain[-1])
            if not chain or chain[-1] not in SIM_FIELDS:
                continue
            root_ok = (chain[0] in roots
                       or (self_cfg and len(chain) >= 2
                           and chain[0] == "self"
                           and (chain[1] in roots
                                or len(chain) == 2)))
            if not root_ok:
                continue
            reads.setdefault(chain[-1], []).append(
                f"{relfile}:{sub.lineno}")
    return reads


def _find_funcs(tree, names):
    found = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            found.append(node)
    return found


def fields_read(spec: dict, whole_modules=()) -> dict:
    """Union the per-function reads over a {relfile: (funcs,)} spec."""
    reads: dict = {}
    for relfile, funcs in spec.items():
        path = os.path.join(REPO_ROOT, relfile)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        nodes = _find_funcs(tree, set(funcs))
        for fld, locs in _collect_reads(nodes, relfile).items():
            reads.setdefault(fld, []).extend(locs)
    for relfile in whole_modules:
        path = os.path.join(REPO_ROOT, relfile)
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
        for fld, locs in _collect_reads([tree], relfile).items():
            reads.setdefault(fld, []).extend(locs)
    return reads


def fields_read_source(src: str, funcs, relfile="<fixture>.py") -> dict:
    """Fixture entry: reads of an in-memory builder source."""
    tree = ast.parse(src)
    return _collect_reads(_find_funcs(tree, set(funcs)), relfile)


def overlay_bakes_whole_config() -> bool:
    """Structural pin: ``fleet_shape_key``'s overlay branch must still
    key the ENTIRE config (``cfg.replace(seed=0)``) — the one line
    that makes every overlay builder read key-covered."""
    path = os.path.join(REPO_ROOT, "gossip_protocol_tpu/core/fleet.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for fn in _find_funcs(tree, {"fleet_shape_key"}):
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call) \
                    and attr_chain(sub.func)[-1:] == ["replace"] \
                    and [k.arg for k in sub.keywords] == ["seed"]:
                return True
    return False


def builder_fields() -> dict:
    return fields_read(BUILDER_FUNCS)


def covered_fields() -> set:
    covered = set(fields_read(KEY_FUNCS))
    covered |= set(fields_read(DATA_FUNCS, whole_modules=DATA_MODULES))
    # ``seed`` never keys anything by design: it flows through the
    # Schedule arrays / per-lane PRNG keys on every path
    covered.add("seed")
    return covered


def canonical_builder_fields() -> dict:
    return fields_read(CANON_BUILDER_FUNCS)


def canonical_covered_fields() -> set:
    """Fields safe under the canonical equivalence-class key: folded
    into the canonical key itself (which reads the ladder rung, the
    quantized signature, and the world split), or riding the padded
    Schedule arrays / world planes as per-request DATA — exact
    windows, drop realizations, and runtime world operands all travel
    that second way by design."""
    covered = set(fields_read(CANON_KEY_FUNCS))
    covered |= set(fields_read(DATA_FUNCS, whole_modules=DATA_MODULES))
    covered.add("seed")
    return covered


def canonical_missing_fields(builders: dict | None = None,
                             covered: set | None = None) -> dict:
    """``{field: [builder locations]}`` read by the canonical-path
    builders but neither canonical-key-folded nor schedule data."""
    builders = canonical_builder_fields() if builders is None else builders
    covered = canonical_covered_fields() if covered is None else covered
    return {f: locs for f, locs in sorted(builders.items())
            if f not in covered}


def missing_fields(builders: dict | None = None,
                   covered: set | None = None) -> dict:
    """``{field: [builder locations]}`` read by builders but neither
    key-folded nor schedule data."""
    builders = builder_fields() if builders is None else builders
    covered = covered_fields() if covered is None else covered
    return {f: locs for f, locs in sorted(builders.items())
            if f not in covered}


def check() -> list[Finding]:
    findings = []
    if not overlay_bakes_whole_config():
        findings.append(Finding(
            "cache-key-complete",
            "gossip_protocol_tpu/core/fleet.py:fleet_shape_key",
            "the overlay branch no longer bakes cfg.replace(seed=0) "
            "— every overlay builder read just lost its key "
            "coverage; restore the whole-config key or enumerate "
            "the overlay fields explicitly"))
    for fld, locs in missing_fields().items():
        findings.append(Finding(
            "cache-key-complete", locs[0],
            f"SimConfig.{fld} is read by a traced builder but folded "
            "into NO cache key (fleet_shape_key / plan_signature / "
            "worlds_key / bucket_key) and is not schedule data — two "
            f"configs differing only in {fld!r} can be served one "
            f"stale program (all readers: {', '.join(sorted(set(locs)))})"))
    for fld, locs in canonical_missing_fields().items():
        findings.append(Finding(
            "canon-key-complete", locs[0],
            f"SimConfig.{fld} is read by a canonical-path builder but "
            "folded into NO canonical key component "
            "(canonical_fleet_shape_key / quantized_plan_signature / "
            "canonical_world_key) and is not schedule data — two "
            f"requests differing only in {fld!r} can land in one "
            "equivalence class and share one stale canonical program "
            f"(all readers: {', '.join(sorted(set(locs)))})"))
    return findings
