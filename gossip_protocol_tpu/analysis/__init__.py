"""Static invariant analysis for the tpu-gossip stack.

The engine/serving stack is held together by structural invariants
that are documented in docs/PERF.md and docs/SERVING.md but — until
this package — enforced almost nowhere:

* the shared clock and the shared drop plane must ride UNBATCHED, or
  every clock/window ``lax.cond`` silently degrades to a
  both-branches ``select_n`` (PERF §8/§10 — measured +43% wall for
  the re-slot cond, 2.6x the whole dense tick for the drop draw);
* the mesh tick body must issue ZERO collectives (lane sharding is
  plain data parallelism, PERF §10);
* host staging paths must be pure numpy — one eager ``jnp`` scalar on
  the pack/resolve path can serialize the whole pipelined scheduler
  behind the in-flight program (PERF §11's silent serializers);
* every stochastic draw must be a pure ``(seed, idx)`` function, or
  the chaos/scenario replay digests stop meaning anything;
* every config field a traced builder reads must be folded into its
  compile-cache key (or flow through the Schedule arrays as data), or
  a stale program can serve wrong results.

Each of these was originally found BY HAND after it cost a
regression.  This package turns the whole bug class into machine
checks, four passes deep:

* :mod:`.jaxpr_audit` — rules over ``jax.make_jaxpr`` output of the
  registered hot programs (solo tick, fleet scan, lane-mesh program,
  2-D lanes×peers prototype, grid kernel, checkpoint-leg resume);
* :mod:`.sharding_flow` — a dataflow pass over the same registry
  propagating per-value mesh-axis sharding and holding every
  collective to per-axis contracts (zero on lanes, budgeted on
  peers, replicated plane stays replicated, specs stay derivable);
* :mod:`.purity_lint` — repo-specific AST rules over the package
  source (wall-clock/unseeded-RNG bans in pure paths, numpy-only
  staging, no in-place writes on host views) plus the cache-key
  completeness scan (:mod:`.cache_keys`);
* :mod:`.guards` — runtime context managers (``jax.transfer_guard``
  wrapping, compile-count budgets) wired into ``bench.py --check``
  and the tier-1 tests.

Run everything: ``python -m gossip_protocol_tpu.analysis`` (exits
nonzero on any finding; see ``--help`` for running a single pass or
rule).  The rule catalog with the motivating regression behind each
rule lives in docs/ANALYSIS.md.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class Finding:
    """One rule violation, with enough provenance to act on it."""

    rule: str     # rule name from the catalog below
    where: str    # program name or file:line
    detail: str   # what is wrong, in one sentence
    path: str = ""  # eqn path inside a jaxpr / function name in a file

    def __str__(self) -> str:
        loc = f"{self.where}" + (f" [{self.path}]" if self.path else "")
        return f"{self.rule}: {loc}\n    {self.detail}"


@dataclass
class RuleInfo:
    """Catalog entry: what a rule protects and where it came from."""

    name: str
    pass_name: str   # "jaxpr" | "sharding" | "ast" | "guard"
    protects: str
    origin: str      # the regression / PR that motivated it


#: The rule catalog.  docs/ANALYSIS.md is the prose version; the CLI
#: prints this table with --list.
RULES: tuple[RuleInfo, ...] = (
    RuleInfo("cond-stays-cond", "jaxpr",
             "shared clock / shared drop plane keep window lax.conds "
             "real conds (no both-branches select_n)",
             "PR 2 (+43% re-slot wall), PR 3 (2.6x batched drop draw), "
             "PR 4 (mesh jaxpr pin, PERF §8/§10)"),
    RuleInfo("zero-collectives-per-tick", "jaxpr",
             "the lane-mesh tick body issues no psum/all_gather/"
             "ppermute — lane sharding stays zero-collective",
             "PR 4 (PERF §10: lanes are plain data parallelism)"),
    RuleInfo("donation-taken", "jaxpr",
             "donated scan carries are actually marked donated in the "
             "lowered computation (input/output aliased)",
             "PR 2 (donated fleet carry), PR 6 (donation-hold "
             "protocol, PERF §11)"),
    RuleInfo("no-transfer-in-scan", "jaxpr",
             "no device_put / host callback primitives inside the "
             "registered hot programs' scanned bodies",
             "PR 6 (the three silent host/device serializers, "
             "PERF §11)"),
    RuleInfo("no-wall-clock-in-pure-paths", "ast",
             "worlds/faults/traffic/scenarios draw only from seeded "
             "(seed, idx) RNG keys; no time.* calls, no mutable RNG",
             "PR 5/7/9 (digest-for-digest chaos and scenario replay)"),
    RuleInfo("host-staging-is-numpy", "ast",
             "schedule builders, host lane stacking, and checkpoint "
             "snapshot/stitch stay free of jnp/eager device ops",
             "PR 6 (eager-op queue serializer #2, PERF §11)"),
    RuleInfo("no-inplace-on-host-views", "ast",
             "no slice/ellipsis writes into arrays aliased from "
             "result/metric attributes (host views of device arrays)",
             "PR 5 (poison wrote into a read-only overlay metrics "
             "view and validation never ran)"),
    RuleInfo("cache-key-complete", "ast",
             "every SimConfig field a traced builder reads is folded "
             "into its compile-cache/bucket key or flows through the "
             "Schedule arrays as data",
             "PR 1/3 (plan-signature cache keys; stale-program class)"),
    RuleInfo("canon-key-complete", "ast",
             "every SimConfig field a canonical-path builder reads is "
             "folded into the equivalence-class key (ladder rung, "
             "quantized signature, world split) or rides the padded "
             "Schedule/world planes as per-request data",
             "PR 16 (bucket canonicalization: one program per class "
             "must stay bit-identical per member)"),
    RuleInfo("lanes-axis-zero-collectives", "sharding",
             "no collective runs over a zero-collective (lane) axis "
             "of a mesh program — the axis-aware successor of "
             "zero-collectives-per-tick, so the 2-D lanes×peers "
             "program can be certified at all",
             "PR 14 (the 2-D mesh gate; PERF §10: lanes are plain "
             "data parallelism)"),
    RuleInfo("peers-axis-collective-budget", "sharding",
             "the peer-axis exchange inside the scanned tick body "
             "stays within its declared static per-eqn budget "
             "(1 all_to_all + 3 ppermute + 1 psum for the dense "
             "RingComm tick) — a bust is a per-tick regression",
             "PR 14 (PERF §4's ring cost, held constant by contract)"),
    RuleInfo("replicated-plane-stays-replicated", "sharding",
             "clock/drop-plane values carry no mesh axis anywhere on "
             "their def-use chain: unsharded at the shard_map "
             "boundary, device-invariant cond predicates, no scan-"
             "carry widening — the static generalization of the "
             "cond-degradation twin test",
             "PR 14 (PR 3's shared-drop rule + PR 4's mesh pin, "
             "per-axis edition)"),
    RuleInfo("spec-derivation-consistent", "sharding",
             "the traced shard_map in_names match the specs derived "
             "independently from the fleet vmap-axes trees (composed "
             "with the peer spec trees for 2-D), failing with the "
             "offending leaf path",
             "PR 14 (PERF §10: 2-D specs must stay derivable, never "
             "hand-maintained)"),
    RuleInfo("journal-before-mutation", "ast",
             "every code path that sets a request's terminal status "
             "under a run_dir store is dominated by the matching "
             "write-ahead journal append (scheduler + recovery)",
             "PR 12 (the crash-window lesson: status visible before "
             "its outcome record loses the request on restart)"),
    RuleInfo("no-recompile-steady-state", "guard",
             "a warmed serving/bench lap triggers zero fresh XLA "
             "compiles (compile-count budget)",
             "PR 6 (first-lap discipline, PERF §11); bench.py --check"),
    RuleInfo("no-implicit-transfer-in-resolve", "guard",
             "device-resident segments (launched program + resolve) "
             "perform no implicit host<->device transfers",
             "PR 6 (resolve must be device-op-free; PERF §11)"),
)


def rule_names() -> list[str]:
    return [r.name for r in RULES]


def run_all(passes=("jaxpr", "sharding", "ast"), rules=None) -> list[Finding]:
    """Run the static passes and return every finding.

    ``passes`` selects jaxpr / sharding / ast (the guard pass is
    runtime-shaped: it runs inside bench.py --check and the tier-1
    tests, not here — but ``python -m gossip_protocol_tpu.analysis
    --pass guard`` runs its self-check).  ``rules`` optionally
    restricts to a subset of rule names.  The sharding pass runs
    after jaxpr so it reuses the jaxpr pass's traced registry instead
    of tracing it twice.
    """
    findings: list[Finding] = []
    if "jaxpr" in passes:
        from . import jaxpr_audit
        findings += jaxpr_audit.audit(rules=rules)
    if "sharding" in passes:
        from . import sharding_flow
        findings += sharding_flow.check(rules=rules)
    if "ast" in passes:
        from . import purity_lint
        findings += purity_lint.lint(rules=rules)
        if rules is None or {"cache-key-complete",
                             "canon-key-complete"} & set(rules):
            from . import cache_keys
            findings += [f for f in cache_keys.check()
                         if rules is None or f.rule in rules]
    if "guard" in passes:
        from . import guards
        findings += guards.self_check(rules=rules)
    return findings


__all__ = ["Finding", "RuleInfo", "RULES", "rule_names", "run_all"]
