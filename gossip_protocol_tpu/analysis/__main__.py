"""CLI: ``python -m gossip_protocol_tpu.analysis``.

Runs the three invariant passes over the tree and exits nonzero on
any finding.  ``--list`` prints the rule catalog; ``--pass``/
``--rule`` restrict the run (``make lint`` runs the two static
passes; the guard pass self-checks its machinery — its real
enforcement points are ``bench.py --check`` and tier-1).

The jaxpr pass traces the lane-mesh programs, which need >= 2
devices: virtual CPU devices are forced below BEFORE jax first
imports, mirroring tests/conftest.py and the smoke scripts.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_virtual_devices():
    """Re-exec once with virtual CPU devices forced.

    ``python -m gossip_protocol_tpu.analysis`` imports the parent
    package (which imports jax) BEFORE this module runs, so setting
    XLA_FLAGS here cannot take effect in-process — the mesh audit
    entries would silently skip.  One guarded re-exec with the
    corrected environment fixes it; explicit user-set flags are
    respected as-is."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags \
            or os.environ.get("_GOSSIP_ANALYSIS_REEXEC") == "1":
        return
    os.environ["_GOSSIP_ANALYSIS_REEXEC"] = "1"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    os.execv(sys.executable,
             [sys.executable, "-m", "gossip_protocol_tpu.analysis"]
             + sys.argv[1:])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gossip_protocol_tpu.analysis",
        description="static invariant analysis (docs/ANALYSIS.md)")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=("jaxpr", "ast", "guard"),
                    help="run only this pass (repeatable; default: "
                         "jaxpr + ast + guard)")
    ap.add_argument("--rule", action="append",
                    help="run only this rule (repeatable)")
    ap.add_argument("--list", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    from . import RULES, rule_names, run_all
    if args.list:
        for r in RULES:
            print(f"{r.name:32s} [{r.pass_name}]  {r.protects}")
            print(f"{'':32s}   origin: {r.origin}")
        return 0

    passes = tuple(args.passes) if args.passes \
        else ("jaxpr", "ast", "guard")
    rules = tuple(args.rule) if args.rule else None
    if rules is not None:
        # a typo'd --rule silently checking NOTHING while exiting 0
        # would green-light a CI gate forever; reject it loudly, and
        # reject a rule whose pass is deselected for the same reason
        known = set(rule_names())
        unknown = [r for r in rules if r not in known]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; see --list")
        runnable = {r.name for r in RULES if r.pass_name in passes}
        dead = [r for r in rules if r not in runnable]
        if dead:
            ap.error(f"rule(s) {dead} are not in the selected "
                     f"pass(es) {list(passes)} — this run would "
                     "check nothing; drop --pass or fix --rule")
    findings = run_all(passes=passes, rules=rules)

    active = [r.name for r in RULES
              if r.pass_name in set(passes)
              and (rules is None or r.name in rules)]
    print(f"analysis: {len(active)} rule(s) over passes "
          f"{'+'.join(passes)}: {', '.join(active)}")
    if "jaxpr" in passes:
        from .jaxpr_audit import audit as _audit
        for p in _audit.last_programs:
            state = "skipped" if p.jaxpr is None else \
                f"{len(p.rules)} rule(s)"
            note = f"  ({p.notes})" if p.notes else ""
            print(f"  program {p.name}: {state}{note}")
    if findings:
        print(f"\n{len(findings)} finding(s):\n", file=sys.stderr)
        for f in findings:
            print(f"  {f}\n", file=sys.stderr)
        return 1
    print("clean: no findings")
    return 0


if __name__ == "__main__":
    # must precede main(): re-execs once (never on plain import — a
    # module-level execv would hijack any process that imports this
    # file for its main())
    _force_virtual_devices()
    # `analysis --list | head` must not traceback on the closed pipe
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main())
