"""CLI: ``python -m gossip_protocol_tpu.analysis``.

Runs the four invariant passes over the tree and exits nonzero on
any finding.  ``--list`` prints the rule catalog; ``--pass``/
``--rule`` restrict the run (``make lint`` runs the three static
passes; the guard pass self-checks its machinery — its real
enforcement points are ``bench.py --check`` and tier-1).  ``--json``
emits one machine-readable document (rule, program/file:line, eqn
path, plus the covered-program roster) for CI and
``scripts/bench_trajectory.py`` — ``make lint-json``.

The jaxpr/sharding passes trace the mesh programs, which need up to
8 devices (the 2-D lanes×peers prototype): virtual CPU devices are
forced below BEFORE jax first imports, mirroring tests/conftest.py
and the smoke scripts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_STATIC_PASSES = ("jaxpr", "sharding", "ast", "guard")


def _force_virtual_devices():
    """Re-exec once with virtual CPU devices forced.

    ``python -m gossip_protocol_tpu.analysis`` imports the parent
    package (which imports jax) BEFORE this module runs, so setting
    XLA_FLAGS here cannot take effect in-process — the mesh audit
    entries would silently skip.  One guarded re-exec with the
    corrected environment fixes it; explicit user-set flags are
    respected as-is.  The full ``sys.argv[1:]`` rides through the
    re-exec, so ``--pass``/``--rule``/``--json`` survive it
    (tests/test_analysis.py pins this), and an exec that fails exits
    nonzero instead of silently green-lighting the caller."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags \
            or os.environ.get("_GOSSIP_ANALYSIS_REEXEC") == "1":
        return
    os.environ["_GOSSIP_ANALYSIS_REEXEC"] = "1"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        os.execv(sys.executable,
                 [sys.executable, "-m", "gossip_protocol_tpu.analysis"]
                 + sys.argv[1:])
    except OSError as e:
        print(f"analysis: re-exec with virtual devices failed ({e}); "
              "refusing to continue with the mesh entries silently "
              "skipped", file=sys.stderr)
        raise SystemExit(2)


def _program_roster() -> list[dict]:
    """The traced-program roster (covered AND skipped) from whichever
    pass last built it — visibility into what the run actually
    checked is part of the contract (a mesh entry skipping for want
    of devices must never read as a pass)."""
    from .jaxpr_audit import audit as _audit
    from .sharding_flow import check as _scheck
    progs = _audit.last_programs or _scheck.last_programs
    roster = []
    for p in progs:
        roster.append({
            "name": p.name,
            "state": "skipped" if p.jaxpr is None else "traced",
            "rules": list(p.rules),
            "sharding_contract": getattr(p, "contract", None)
            is not None,
            "notes": p.notes,
        })
    return roster


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gossip_protocol_tpu.analysis",
        description="static invariant analysis (docs/ANALYSIS.md)")
    ap.add_argument("--pass", dest="passes", action="append",
                    choices=_STATIC_PASSES,
                    help="run only this pass (repeatable; default: "
                         "jaxpr + sharding + ast + guard)")
    ap.add_argument("--rule", action="append",
                    help="run only this rule (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON document "
                         "(findings + covered-program roster) instead "
                         "of the human report; exit code unchanged")
    ap.add_argument("--list", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    from . import RULES, rule_names, run_all
    if args.list:
        for r in RULES:
            print(f"{r.name:32s} [{r.pass_name}]  {r.protects}")
            print(f"{'':32s}   origin: {r.origin}")
        return 0

    passes = tuple(args.passes) if args.passes else _STATIC_PASSES
    rules = tuple(args.rule) if args.rule else None
    if rules is not None:
        # a typo'd --rule silently checking NOTHING while exiting 0
        # would green-light a CI gate forever; reject it loudly, and
        # reject a rule whose pass is deselected for the same reason
        known = set(rule_names())
        unknown = [r for r in rules if r not in known]
        if unknown:
            ap.error(f"unknown rule(s) {unknown}; see --list")
        runnable = {r.name for r in RULES if r.pass_name in passes}
        dead = [r for r in rules if r not in runnable]
        if dead:
            ap.error(f"rule(s) {dead} are not in the selected "
                     f"pass(es) {list(passes)} — this run would "
                     "check nothing; drop --pass or fix --rule")
    findings = run_all(passes=passes, rules=rules)

    active = [r.name for r in RULES
              if r.pass_name in set(passes)
              and (rules is None or r.name in rules)]
    traces = {"jaxpr", "sharding"} & set(passes)

    if args.json:
        payload = {
            "ok": not findings,
            "passes": list(passes),
            "rules": active,
            "programs": _program_roster() if traces else [],
            "findings": [{"rule": f.rule, "where": f.where,
                          "detail": f.detail, "path": f.path}
                         for f in findings],
            "count": len(findings),
        }
        print(json.dumps(payload, indent=1))
        return 1 if findings else 0

    print(f"analysis: {len(active)} rule(s) over passes "
          f"{'+'.join(passes)}: {', '.join(active)}")
    if traces:
        for p in _program_roster():
            state = "skipped" if p["state"] == "skipped" else \
                f"{len(p['rules'])} rule(s)" \
                + (" + sharding contract"
                   if p["sharding_contract"] else "")
            note = f"  ({p['notes']})" if p["notes"] else ""
            print(f"  program {p['name']}: {state}{note}")
    if findings:
        print(f"\n{len(findings)} finding(s):\n", file=sys.stderr)
        for f in findings:
            print(f"  {f}\n", file=sys.stderr)
        return 1
    print("clean: no findings")
    return 0


if __name__ == "__main__":
    # must precede main(): re-execs once (never on plain import — a
    # module-level execv would hijack any process that imports this
    # file for its main())
    _force_virtual_devices()
    # `analysis --list | head` must not traceback on the closed pipe
    import signal
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    raise SystemExit(main())
