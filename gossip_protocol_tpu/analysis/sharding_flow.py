"""Sharding-flow pass: per-axis collective rules over mesh programs.

The jaxpr audit's ``zero-collectives-per-tick`` is axis-blind: it bans
EVERY collective, which is correct for the 1-D lane mesh (lanes are
plain data parallelism, PERF §10) but *wrong* for the peer axis — the
XOR-exchange ``all_to_all``/``ppermute`` ring there is the whole point
(PERF §4).  The 2-D ``Mesh((lanes, peers))`` composition the ROADMAP
names as the biggest unclaimed scale unlock therefore cannot be
certified by the old rule at all.  This pass grows the axis awareness:

A small abstract interpreter propagates, for every value inside a
``shard_map`` body, the set of mesh axis names the value is
*device-varying* over — seeded from the traced ``in_names``, joined
through each equation, removed by cross-axis reductions
(``psum``/``all_gather``), introduced by ``axis_index``, and carried
to fixpoint through ``scan``/``while`` bodies and ``cond`` branches.
Every collective equation is attributed to the concrete axis name in
its params.  Four rules consume the walk (each registered program
declares a :class:`ShardingContract`):

``lanes-axis-zero-collectives``
    No collective may name a zero-collective axis (the lane axis).
    The old rule, scoped per axis: the 2-D program's peer collectives
    pass, a collective smuggled onto ``lanes`` fires.

``peers-axis-collective-budget``
    The sharded exchange inside the scanned tick body carries a
    declared STATIC per-tick equation budget per axis (the dense
    RingComm tick: 1 ``all_to_all`` + 3 ``ppermute`` + 1 ``psum``).
    A bust means a per-tick regression — a collective added to the
    hot loop — not a one-off; collectives over an axis with no
    declared budget fire unconditionally.

``replicated-plane-stays-replicated``
    The clock/drop-plane leaves must enter the shard_map with NO mesh
    axis (their ``in_names`` entry is empty), every ``cond`` predicate
    inside the body must be device-invariant (a varying predicate
    means the shared window cond diverges per device — the static
    generalization of the cond-degradation twin test), and a scan
    carry slot that enters device-invariant must exit that way (the
    clock's def-use chain across ticks).

``spec-derivation-consistent``
    The traced ``in_names`` must equal the dims derived independently
    from the fleet's vmap-axes trees (composed with the peer-axis
    spec trees for the 2-D program) — failing with the offending leaf
    path.  Closed-over inputs hoisted ahead of the arg tree must be
    replicated.

Run: ``python -m gossip_protocol_tpu.analysis --pass sharding`` (the
CLI forces 8 virtual CPU devices so the 2-D prototype traces on a
bare box).  Catalog: docs/ANALYSIS.md.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

from . import Finding
from .jaxpr_audit import COLLECTIVE_PRIMS, iter_eqns

#: collectives whose RESULT is device-invariant over the named axes
#: (a cross-axis reduction/gather); everything else — ppermute,
#: all_to_all, pgather, reduce_scatter — keeps (or adds) the axis
_REDUCING_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "pmean",
    "all_gather", "all_gather_invariant",
})


# ---- the per-program contract ----------------------------------------
@dataclass(frozen=True)
class ShardingContract:
    """What a registered mesh program promises about its axes.

    ``expected_in_names`` is the independently derived flat spec
    list: one ``(leaf_path, {dim: (axis, ...)})`` per flattened arg
    leaf, aligned with the TAIL of the shard_map's ``in_names``
    (tracing may hoist closed-over constants ahead of the args —
    those must be replicated)."""

    mesh_axes: tuple
    zero_collective_axes: tuple = ("lanes",)
    #: axis -> max STATIC collective eqns inside the scanned tick body
    budgets: dict = field(default_factory=dict)
    #: leaf paths (``state.tick``, ``sched.drop_active``, ...) that
    #: must stay device-invariant end to end
    replicated_plane: tuple = ()
    expected_in_names: tuple = ()


# ---- contract derivation helpers (registry side) ---------------------
def spec_to_dims(spec) -> dict:
    """PartitionSpec -> ``{dim: (axis, ...)}`` (None entries elided)."""
    out = {}
    for i, part in enumerate(spec):
        if part is None:
            continue
        out[i] = (part,) if isinstance(part, str) else tuple(part)
    return out


def axes_tree_dims(prefix: str, axes_tree, lane_axis: str = "lanes",
                   peer_specs=None) -> tuple:
    """Derive the expected per-leaf ``in_names`` dims from a vmap axes
    tree, optionally composed with a peer-axis PartitionSpec tree.

    This mirrors — *independently of* — the builders' own spec
    derivation (``fleet_mesh._axes_to_specs`` for 1-D,
    ``fleet_mesh.compose_lane_peer_specs`` for 2-D): a lane-batched
    leaf is lane-sharded on its new leading dim (shifting any peer
    dims right by one); an unbatched leaf (the clock, the shared drop
    plane) carries only its peer dims — none, for the replicated
    plane.  If a builder's derivation drifts from this one, the
    ``spec-derivation-consistent`` rule fires with the leaf path."""
    entries = []
    for f in dataclasses.fields(type(axes_tree)):
        batched = getattr(axes_tree, f.name) is not None
        pd = spec_to_dims(getattr(peer_specs, f.name)) \
            if peer_specs is not None else {}
        if batched:
            d = {0: (lane_axis,)}
            d.update({k + 1: v for k, v in pd.items()})
        else:
            d = pd
        entries.append((f"{prefix}.{f.name}", d))
    return tuple(entries)


def all_batched_dims(prefix: str, cls, lane_axis: str = "lanes") -> tuple:
    """Every field of ``cls`` lane-sharded on its leading dim (the
    overlay mesh schedule: vmap ``in_axes=0`` across the board)."""
    return tuple((f"{prefix}.{f.name}", {0: (lane_axis,)})
                 for f in dataclasses.fields(cls))


# ---- the abstract interpreter ----------------------------------------
class _Trace:
    """Everything one body walk collects for the rules."""

    def __init__(self):
        self.collectives = []   # (path_str, prim_name, axes tuple)
        self.cond_preds = []    # (path_str, axes frozenset)
        self.widened = []       # (path_str, slot, aval str, axes)


def collective_axes(eqn) -> tuple:
    """The mesh axis names a collective eqn runs over, normalized
    across the primitives' inconsistent param spellings (``ppermute``:
    ``axis_name=('peers',)``; ``all_to_all``: ``axis_name='peers'``;
    ``psum``: ``axes=('peers',)`` — verified on jax 0.4.37).
    Positional (integer) axes are not mesh axes and are elided."""
    raw = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(raw, str):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def _flow(jaxpr, in_sets, path, trace):
    """Propagate device-varying axis-sets through one jaxpr.

    Returns the outvars' axis-sets.  ``trace=None`` mutes reporting
    (fixpoint iterations walk bodies repeatedly; only the final
    post-fixpoint pass records collectives/predicates)."""
    env = {}

    def get(atom):
        # Literals carry .val and are device-invariant by definition
        return frozenset() if hasattr(atom, "val") \
            else env.get(atom, frozenset())

    for v, s in zip(jaxpr.invars, in_sets):
        env[v] = s
    for v in jaxpr.constvars:
        env[v] = frozenset()

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        ins = [get(a) for a in eqn.invars]
        joined = frozenset().union(*ins) if ins else frozenset()
        outs = None

        if name in COLLECTIVE_PRIMS:
            axes = collective_axes(eqn)
            if trace is not None:
                trace.collectives.append(
                    ("/".join(path) or "<top>", name, axes))
            if name in _REDUCING_PRIMS:
                res = joined - set(axes)
            else:
                res = joined | set(axes)
            outs = [res] * len(eqn.outvars)

        elif name == "axis_index":
            # introduces device variation from thin air
            outs = [frozenset(collective_axes(eqn))] * len(eqn.outvars)

        elif name == "scan":
            nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
            body = eqn.params["jaxpr"].jaxpr
            consts, carry, xs = ins[:nc], ins[nc:nc + nk], ins[nc + nk:]
            entry = list(carry)
            sub = path + ("scan.jaxpr",)
            for _ in range(len(carry) + 1):
                res = _flow(body, consts + carry + xs, sub, None)
                new = [c | r for c, r in zip(carry, res[:nk])]
                if new == carry:
                    break
                carry = new
            res = _flow(body, consts + carry + xs, sub, trace)
            carry = [c | r for c, r in zip(carry, res[:nk])]
            if trace is not None:
                for i, (before, after) in enumerate(zip(entry, carry)):
                    if not before and after:
                        trace.widened.append(
                            ("/".join(sub), i,
                             str(eqn.invars[nc + i].aval), after))
            outs = carry + res[nk:]

        elif name == "while":
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            cc, bc, carry = ins[:cn], ins[cn:cn + bn], ins[cn + bn:]
            body = eqn.params["body_jaxpr"].jaxpr
            sub = path + ("while.body_jaxpr",)
            for _ in range(len(carry) + 1):
                res = _flow(body, bc + carry, sub, None)
                new = [c | r for c, r in zip(carry, res)]
                if new == carry:
                    break
                carry = new
            res = _flow(body, bc + carry, sub, trace)
            carry = [c | r for c, r in zip(carry, res)]
            # the loop condition can hide a collective too
            _flow(eqn.params["cond_jaxpr"].jaxpr, cc + carry,
                  path + ("while.cond_jaxpr",), trace)
            outs = carry

        elif name == "cond":
            pred, ops = ins[0], ins[1:]
            if trace is not None:
                trace.cond_preds.append(("/".join(path) or "<top>",
                                         pred))
            branch_outs = None
            for br in eqn.params["branches"]:
                res = _flow(br.jaxpr, ops, path + ("cond.branches",),
                            trace)
                branch_outs = res if branch_outs is None \
                    else [a | b for a, b in zip(branch_outs, res)]
            # outputs data-depend on the predicate as well
            outs = [o | pred for o in branch_outs]

        else:
            # generic call-like eqns (pjit, closed_call, custom_jvp/
            # vjp, remat) recurse when the inner arity matches; any
            # other eqn joins conservatively (sound upper bound —
            # only the explicit reductions above REMOVE an axis)
            inner = None
            for k in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
                v = eqn.params.get(k)
                if v is None:
                    continue
                j = v.jaxpr if hasattr(v, "jaxpr") else v
                if hasattr(j, "invars") \
                        and len(j.invars) == len(eqn.invars):
                    inner = (k, j)
                    break
            if inner is not None:
                outs = _flow(inner[1], ins,
                             path + (f"{name}.{inner[0]}",), trace)
            else:
                outs = [joined] * len(eqn.outvars)

        for v, s in zip(eqn.outvars, outs):
            env[v] = s
    return [get(v) for v in jaxpr.outvars]


# ---- shard_map introspection -----------------------------------------
def _eqn_in_dims(eqn) -> list:
    """Normalized per-invar ``{dim: (axis, ...)}`` of a shard_map eqn
    (0.4.x spells it ``in_names`` as tuple-of-dicts; newer jax may
    carry PartitionSpecs under ``in_specs``)."""
    if "in_names" in eqn.params:
        return [{int(k): tuple(v) for k, v in d.items()}
                for d in eqn.params["in_names"]]
    return [spec_to_dims(s) for s in eqn.params["in_specs"]]


def _shard_map_eqns(closed_jaxpr):
    return [(p, e) for p, e in iter_eqns(closed_jaxpr.jaxpr)
            if e.primitive.name == "shard_map"]


# ---- the rules --------------------------------------------------------
def check_program(prog, rules=None) -> list[Finding]:
    """All four sharding rules over one contract-carrying program."""
    c = getattr(prog, "contract", None)
    if c is None or prog.jaxpr is None:
        return []

    def want(r):
        return rules is None or r in rules

    out: list[Finding] = []
    sms = _shard_map_eqns(prog.jaxpr)
    if not sms:
        out.append(Finding(
            "spec-derivation-consistent", prog.name,
            "program declares a sharding contract but lowers no "
            "shard_map equation — the mesh program stopped being a "
            "mesh program",
            path=prog.provenance))
        return out

    for path, eqn in sms:
        pstr = "/".join(path) or "<top>"
        names = _eqn_in_dims(eqn)
        inner = eqn.params["jaxpr"]
        inner = inner.jaxpr if hasattr(inner, "jaxpr") else inner

        # ---- spec derivation + alignment --------------------------
        exp = c.expected_in_names
        aligned = None
        if exp and len(names) < len(exp):
            if want("spec-derivation-consistent"):
                out.append(Finding(
                    "spec-derivation-consistent", prog.name,
                    f"shard_map takes {len(names)} inputs but the "
                    f"spec tree derived from the vmap-axes trees has "
                    f"{len(exp)} leaves — the in tree no longer "
                    "matches the derivation",
                    path=pstr))
        elif exp:
            lead = names[:len(names) - len(exp)]
            aligned = names[len(names) - len(exp):]
            if want("spec-derivation-consistent"):
                for i, d in enumerate(lead):
                    if d:
                        out.append(Finding(
                            "spec-derivation-consistent", prog.name,
                            f"closed-over input {i} enters the "
                            f"shard_map sharded {d} — hoisted "
                            "constants must be replicated",
                            path=pstr))
                for (leaf, want_d), got in zip(exp, aligned):
                    if got != want_d:
                        out.append(Finding(
                            "spec-derivation-consistent", prog.name,
                            f"leaf {leaf}: traced in_names {got} != "
                            f"{want_d} derived from the vmap-axes "
                            "trees (compose_lane_peer_specs / "
                            "_axes_to_specs drifted from the axes "
                            "trees)",
                            path=pstr))

        # ---- replicated plane: the declared leaves enter unsharded
        if want("replicated-plane-stays-replicated") \
                and aligned is not None:
            for (leaf, _), got in zip(exp, aligned):
                if leaf in c.replicated_plane and got:
                    axes = sorted({a for v in got.values() for a in v})
                    out.append(Finding(
                        "replicated-plane-stays-replicated", prog.name,
                        f"replicated-plane leaf {leaf} enters the "
                        f"shard_map sharded over {axes} — the shared "
                        "clock/drop plane must be device-invariant "
                        "(the PR-3 shared-drop rule, mesh edition)",
                        path=pstr))

        # ---- the dataflow walk ------------------------------------
        seeds = [frozenset(a for axs in d.values() for a in axs)
                 for d in names]
        tr = _Trace()
        _flow(inner, seeds, path + ("shard_map.jaxpr",), tr)

        if want("lanes-axis-zero-collectives"):
            for p, prim, axes in tr.collectives:
                bad = sorted(set(axes) & set(c.zero_collective_axes))
                if bad:
                    out.append(Finding(
                        "lanes-axis-zero-collectives", prog.name,
                        f"collective {prim!r} runs over zero-"
                        f"collective ax(es) {bad} — the lane axis is "
                        "plain data parallelism and must move zero "
                        "bytes (PERF §10)",
                        path=p))

        if want("peers-axis-collective-budget"):
            counts: dict = {}
            for p, prim, axes in tr.collectives:
                for a in axes:
                    if a in c.zero_collective_axes:
                        continue   # already the lanes rule's finding
                    if a not in c.budgets:
                        out.append(Finding(
                            "peers-axis-collective-budget", prog.name,
                            f"collective {prim!r} over axis {a!r} "
                            "which has no declared per-tick budget — "
                            "declare one in the program's "
                            "ShardingContract or drop the collective",
                            path=p))
                    elif any(seg.startswith("scan")
                             for seg in p.split("/")):
                        counts[a] = counts.get(a, 0) + 1
            for a, budget in c.budgets.items():
                got = counts.get(a, 0)
                if got > budget:
                    out.append(Finding(
                        "peers-axis-collective-budget", prog.name,
                        f"{got} static collective eqn(s) over axis "
                        f"{a!r} inside the scanned tick body exceed "
                        f"the declared per-tick budget of {budget} — "
                        "a collective joined the hot loop (every "
                        "tick now pays it)",
                        path=pstr))

        if want("replicated-plane-stays-replicated"):
            for p, pred in tr.cond_preds:
                if pred:
                    out.append(Finding(
                        "replicated-plane-stays-replicated", prog.name,
                        "cond predicate is device-varying over "
                        f"{sorted(pred)} — the window cond no longer "
                        "runs as ONE shared branch decision across "
                        "the mesh (the cond-degradation bug class, "
                        "sharded edition; PERF §8/§10)",
                        path=p))
            for p, slot, aval, axes in tr.widened:
                out.append(Finding(
                    "replicated-plane-stays-replicated", prog.name,
                    f"scan carry slot {slot} ({aval}) enters device-"
                    f"invariant but exits varying over {sorted(axes)} "
                    "— a replicated-plane value picked up a mesh axis "
                    "on its def-use chain across ticks",
                    path=p))
    return out


# ---- driver -----------------------------------------------------------
SHARDING_RULES = ("lanes-axis-zero-collectives",
                  "peers-axis-collective-budget",
                  "replicated-plane-stays-replicated",
                  "spec-derivation-consistent")


def check(rules=None, mesh_devices: int = 2,
          programs=None) -> list[Finding]:
    """Run the sharding rules over every contract-carrying registered
    program.  Reuses the jaxpr pass's traced roster when it already
    ran this process (``run_all`` orders jaxpr first — tracing the
    registry twice would double the audit's cost); builds it
    otherwise.  The roster is kept on ``check.last_programs`` for the
    CLI's coverage print."""
    if rules is not None and not set(rules) & set(SHARDING_RULES):
        check.last_programs = []
        return []
    if programs is None:
        from . import jaxpr_audit
        programs = jaxpr_audit.audit.last_programs \
            or jaxpr_audit.build_programs(mesh_devices)
    check.last_programs = programs
    findings: list[Finding] = []
    for p in programs:
        findings += check_program(p, rules=rules)
    return findings


check.last_programs = []
