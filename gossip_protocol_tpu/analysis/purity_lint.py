"""AST purity lint: repo-specific source rules over the package.

These rules guard invariants the jaxpr auditor cannot see because
they live in HOST python, not in traced programs:

``no-wall-clock-in-pure-paths``
    The stochastic planes that must replay digest-for-digest —
    worlds.py, service/faults.py, service/traffic.py,
    models/scenarios.py — may draw randomness ONLY from a fresh
    ``numpy.random.default_rng((seed, idx, ...))`` keyed by a tuple,
    and may never call ``time.*`` or mutable/unseeded RNG in a draw
    path.  (Injectable clocks passed as DEFAULT parameters —
    ``now=time.perf_counter`` — are fine: the rule flags calls, not
    references, which is exactly the seam the fake-clock tests use.)

``host-staging-is-numpy``
    The functions PERF §11 declares host-side — schedule builders,
    host lane stacking, checkpoint snapshot/stitch — must stay free
    of ``jnp.``/``jax.numpy`` usage: ONE eager jnp scalar on the pack
    or resolve path dispatches a tiny XLA program that queues behind
    the in-flight fleet program once the client's bounded in-flight
    queue fills (serializer #2 of PERF §11).

``no-inplace-on-host-views``
    No slice/ellipsis writes into arrays aliased from result or
    metric attributes.  Overlay metrics cross to host as READ-ONLY
    numpy views of device arrays; PR 5's poison fault wrote into one
    in place, raised ``ValueError`` before validation ever ran, and
    the whole fault path silently changed meaning.  Writes into
    freshly allocated locals (``np.zeros`` etc.) are fine; writes
    through an attribute chain — or through a local bound via an
    aliasing converter (``np.asarray(lane.metrics.sent)``,
    ``.view()``, ``.reshape()``) — are flagged.

``journal-before-mutation``
    In the durable-serving modules (service/scheduler.py,
    store/recovery.py), every code path that sets a request's
    terminal status (``._complete()`` / ``._fail()``) must be
    textually dominated, within its function, by the matching
    write-ahead ``journal.outcome(...)`` append.  This is PR 12's
    crash-window lesson as a machine check: a terminal status that
    becomes visible to callers BEFORE its outcome record hits the
    journal means a crash in that window re-runs (or loses) the
    request on recovery (docs/SERVING.md).

Findings can be allowlisted in ``analysis/lint_allow.toml`` — every
entry must carry a ``why`` (the file is the audit trail; an
uncommented entry is itself a lint error).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass

from . import Finding
from ._astutil import REPO_ROOT, attr_chain as _attr_chain

#: modules whose draws must be pure (seed, idx) functions
PURE_PATH_MODULES = (
    "gossip_protocol_tpu/worlds.py",
    "gossip_protocol_tpu/service/faults.py",
    "gossip_protocol_tpu/service/traffic.py",
    "gossip_protocol_tpu/models/scenarios.py",
)

#: (module, function) pairs whose BODIES order the deterministic
#: harvest of the per-bucket in-flight rings (PR 17): ring creation
#: order + FIFO within each ring must stay a pure function of the
#: submit/flush sequence, so chaos/elastic digest replays hold at
#: every pipeline_depth.  The whole scheduler module legitimately
#: reads wall clock elsewhere (deadlines, queue-age batching), so the
#: no-wall-clock rule is scoped to exactly these functions rather
#: than the file.  ``_harvest_ready`` is included deliberately: its
#: readiness PROBE is wall-dependent, but that dependence must enter
#: only through ``PendingFleet.is_ready()`` — a direct ``time.*``
#: call (or an RNG tiebreak) in the ordering logic itself is the bug
#: class this guards against.
RING_ORDER_FUNCS = {
    "gossip_protocol_tpu/service/scheduler.py": (
        "_ring_key", "_inflight_batches", "_pop_oldest_inflight",
        "_abort_inflight", "resolve_inflight", "_harvest_ready"),
}

#: (module, function) pairs PERF §11 declares host-numpy-only:
#: schedule builders, host lane stacking, checkpoint snapshot/stitch
HOST_STAGING_FUNCS = {
    "gossip_protocol_tpu/state.py": (
        "make_schedule_host", "slice_schedule"),
    "gossip_protocol_tpu/models/overlay.py": (
        "make_overlay_schedule",),
    "gossip_protocol_tpu/core/fleet.py": (
        "stack_lanes_host", "_embed_state_host", "_lane_state",
        "finish_lane", "_snapshot_lane", "_resume_states",
        "_advance_checkpoints", "_dense_trace_lanes",
        # durable-serving snapshot (de)serialization (PR 12): the
        # spill tier's flatten/rebuild must stay host numpy — a jnp
        # leaf here would put device transfers on the crash-recovery
        # path and break the bit-identity contract
        "checkpoint_arrays", "checkpoint_from_arrays"),
    # the durability subsystem (PR 12, gossip_protocol_tpu/store/):
    # every spill/restore/journal path is host numpy + file IO by
    # contract — recovery must work on a machine with no devices warm
    "gossip_protocol_tpu/store/spill.py": (
        "_arrays_sha", "checkpoint_digest_from_arrays", "save_spill",
        "read_spill", "verify_spill", "inspect_spill", "_spill",
        "ref", "fetch", "materialize"),
    "gossip_protocol_tpu/store/journal.py": (
        "_append", "meta", "submit", "cut", "fault", "outcome",
        "recover_mark", "read_journal"),
    "gossip_protocol_tpu/store/recovery.py": (
        "recover_service",),
    "gossip_protocol_tpu/service/replay.py": (
        # the journal's per-result content digest rides the resolve
        # path (scheduler _complete_batch) — host numpy only
        "result_digest",),
}

#: modules checked for in-place writes on host views (the serving
#: layer's result-handling surface plus the fleet resolve paths)
HOST_VIEW_MODULES = (
    "gossip_protocol_tpu/service/faults.py",
    "gossip_protocol_tpu/service/resilience.py",
    "gossip_protocol_tpu/service/scheduler.py",
    "gossip_protocol_tpu/service/replay.py",
    "gossip_protocol_tpu/service/loadbench.py",
    "gossip_protocol_tpu/core/fleet.py",
    "gossip_protocol_tpu/core/sim.py",
    # the durability subsystem (PR 12): a spilled snapshot's arrays
    # are handed straight back into fleet dispatch — an in-place
    # write anywhere in the store would corrupt resumable state
    "gossip_protocol_tpu/store/spill.py",
    "gossip_protocol_tpu/store/journal.py",
    "gossip_protocol_tpu/store/recovery.py",
    "gossip_protocol_tpu/store/harness.py",
)

#: modules whose terminal-status writers must journal FIRST
#: (recovery.py currently sets no terminal status — it readmits —
#: but stays covered so a future direct setter there is caught)
JOURNAL_ORDER_MODULES = (
    "gossip_protocol_tpu/service/scheduler.py",
    "gossip_protocol_tpu/store/recovery.py",
)

#: the handle methods that make a request's terminal status visible
#: to callers (service/scheduler.py RequestHandle)
_TERMINAL_SETTERS = frozenset({"_complete", "_fail"})


#: converters that can ALIAS their argument (a write through the
#: result can mutate the argument's buffer)
_ALIASING_CONVERTERS = frozenset({
    "asarray", "asanyarray", "ascontiguousarray", "view", "reshape",
    "ravel", "squeeze", "transpose", "atleast_1d", "atleast_2d",
})


# ---- allowlist -------------------------------------------------------
@dataclass
class AllowEntry:
    rule: str
    file: str
    match: str   # substring of the offending source line
    why: str


def _parse_allow_toml(path: str) -> list[AllowEntry]:
    """Minimal TOML-subset reader for lint_allow.toml (``[[allow]]``
    tables of string keys) — python 3.10 has no tomllib and the
    container must not grow dependencies."""
    entries: list[AllowEntry] = []
    cur: dict | None = None
    if not os.path.exists(path):
        return entries
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[allow]]":
                if cur:
                    entries.append(AllowEntry(**cur))
                cur = {}
                continue
            if "=" in line and cur is not None:
                k, v = line.split("=", 1)
                cur[k.strip()] = v.strip().strip('"')
    if cur:
        entries.append(AllowEntry(**cur))
    return entries


def load_allowlist() -> tuple[list[AllowEntry], list[Finding]]:
    """The allowlist plus findings for malformed entries (an entry
    without a ``why`` is itself a violation — the satellite contract:
    the file is empty or every entry is justified)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "lint_allow.toml")
    findings = []
    try:
        entries = _parse_allow_toml(path)
    except TypeError as e:
        return [], [Finding("allowlist", "analysis/lint_allow.toml",
                            f"malformed entry: {e}")]
    for e in entries:
        if not e.why.strip():
            findings.append(Finding(
                "allowlist", "analysis/lint_allow.toml",
                f"entry ({e.rule}, {e.file}, {e.match!r}) has no "
                "'why' — every allowlisted finding must be justified"))
    return entries, findings


def _allowed(entries, rule: str, relfile: str, src_line: str) -> bool:
    return any(e.rule == rule and e.file == relfile
               and e.match and e.match in src_line for e in entries)


# ---- shared AST helpers ----------------------------------------------
def _read_lines(path: str) -> tuple[ast.Module, list[str]]:
    with open(path) as f:
        src = f.read()
    return ast.parse(src, filename=path), src.splitlines()


def _is_region_write(sub: ast.Subscript) -> bool:
    """Slice / Ellipsis / tuple-containing-slice subscript — the
    numpy region-write shapes (``x[...]``, ``x[:, 1]``, ``x[a:b]``)."""
    sl = sub.slice
    if isinstance(sl, ast.Slice):
        return True
    if isinstance(sl, ast.Constant) and sl.value is Ellipsis:
        return True
    if isinstance(sl, ast.Tuple):
        return any(isinstance(e, ast.Slice)
                   or (isinstance(e, ast.Constant)
                       and e.value is Ellipsis)
                   for e in sl.elts)
    return False


# ---- rule: no-wall-clock-in-pure-paths -------------------------------
def _time_aliases(tree) -> tuple[set, set]:
    """(module aliases of ``time``, names imported FROM time) — so
    ``import time as t; t.sleep(...)`` and ``from time import
    perf_counter; perf_counter()`` are caught like the ``time.X()``
    attribute form."""
    mods, names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                names.add(a.asname or a.name)
    return mods, names


def _check_pure_paths(tree, lines, relfile, allow,
                      funcs=None) -> list[Finding]:
    """``funcs=None`` checks the whole module (the PURE_PATH_MODULES
    contract); a tuple of names scopes the rule to those function
    bodies (the RING_ORDER_FUNCS contract — modules that legitimately
    read wall clock elsewhere)."""
    out = []
    time_mods, time_names = _time_aliases(tree)
    if funcs is None:
        nodes = ast.walk(tree)
    else:
        nodes = (sub for node in ast.walk(tree)
                 if isinstance(node, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))
                 and node.name in funcs
                 for sub in ast.walk(node))
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        where = f"{relfile}:{node.lineno}"

        def flag(detail):
            if not _allowed(allow, "no-wall-clock-in-pure-paths",
                            relfile, line):
                out.append(Finding("no-wall-clock-in-pure-paths",
                                   where, detail, path=line.strip()))

        if len(chain) == 2 and chain[0] in (time_mods | {"time"}):
            flag(f"call of time.{chain[1]} in a pure-replay path — "
                 "wall time must enter through an injectable clock "
                 "parameter, never a direct call")
        elif len(chain) == 1 and chain[0] in time_names:
            flag(f"call of {chain[0]} (imported from time) in a "
                 "pure-replay path — wall time must enter through an "
                 "injectable clock parameter, never a direct call")
        elif len(chain) >= 2 and chain[-2:-1] == ["random"] \
                and chain[0] in ("np", "numpy"):
            fn = chain[-1]
            if fn != "default_rng":
                flag(f"np.random.{fn} draws from MUTABLE global RNG "
                     "state — draw from a fresh "
                     "default_rng((seed, idx)) instead")
            elif not (node.args
                      and isinstance(node.args[0], ast.Tuple)):
                flag("default_rng() without a (seed, idx, ...) tuple "
                     "key — the draw is not a pure function of its "
                     "seed plane")
        elif chain == ["default_rng"]:
            if not (node.args and isinstance(node.args[0], ast.Tuple)):
                flag("default_rng() without a (seed, idx, ...) tuple "
                     "key — the draw is not a pure function of its "
                     "seed plane")
        elif chain[:1] == ["random"] and len(chain) == 2:
            flag(f"stdlib random.{chain[1]} call — mutable global "
                 "RNG in a replay path")
    return out


# ---- rule: host-staging-is-numpy -------------------------------------
def _check_host_staging(tree, lines, relfile, funcs, allow
                        ) -> list[Finding]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if node.name not in funcs:
            continue
        for sub in ast.walk(node):
            chain = []
            if isinstance(sub, (ast.Attribute, ast.Name)):
                chain = _attr_chain(sub)
            if not chain:
                continue
            bad = None
            if chain[0] == "jnp" and len(chain) > 1:
                bad = ".".join(chain)
            elif chain[:2] == ["jax", "numpy"]:
                bad = ".".join(chain)
            elif chain[:2] == ["jax", "device_put"]:
                bad = "jax.device_put"
            if bad:
                line = lines[sub.lineno - 1] \
                    if sub.lineno <= len(lines) else ""
                if _allowed(allow, "host-staging-is-numpy", relfile,
                            line):
                    continue
                out.append(Finding(
                    "host-staging-is-numpy",
                    f"{relfile}:{sub.lineno}",
                    f"{bad} inside {node.name}() — this function is "
                    "declared HOST-side (PERF §11): an eager device "
                    "op here queues behind the in-flight fleet "
                    "program and serializes the pipelined scheduler",
                    path=node.name))
                break   # one finding per offending function is enough
    return out


# ---- rule: no-inplace-on-host-views ----------------------------------
def _check_host_views(tree, lines, relfile, allow) -> list[Finding]:
    out = []

    _MODS = ("np", "numpy", "jnp", "jax")

    def aliasing_binding(v, aliased) -> bool:
        """Does this RHS alias foreign (attribute-reached) memory?"""
        if isinstance(v, ast.Attribute) and _attr_chain(v):
            return True
        if not isinstance(v, ast.Call):
            return False
        c = _attr_chain(v.func)
        if not c or c[-1] not in _ALIASING_CONVERTERS:
            return False
        if c[0] in _MODS:
            # free-function converter: aliases iff an argument is an
            # attribute chain — np.asarray(lane.metrics.sent)
            return any(isinstance(a, ast.Attribute) and _attr_chain(a)
                       and _attr_chain(a)[0] not in _MODS
                       for a in v.args)
        # method-form converter (args or not): aliases iff the
        # receiver is itself an attribute chain —
        # lane.metrics.sent.reshape(2, 4) — or a local already known
        # to alias (m2 = m.view()); a bare safe local's method
        # (out.reshape(...)) stays clean
        return len(c) > 2 or (len(c) == 2 and c[0] in aliased)

    def visit(stmts, aliased: dict):
        """In-order statement walk; each function gets a fresh local
        alias map (a closure write-through is out of scope for this
        lint — the allowlist is the escape hatch)."""
        for node in stmts:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                visit(node.body, {})
                continue
            if isinstance(node, ast.ClassDef):
                visit(node.body, {})
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in tgts:
                    if not (isinstance(tgt, ast.Subscript)
                            and _is_region_write(tgt)):
                        continue
                    base = tgt.value
                    hit = None
                    if isinstance(base, ast.Attribute) and \
                            _attr_chain(base) and \
                            _attr_chain(base)[0] not in (
                                "np", "numpy", "jnp", "jax", "self"):
                        hit = ".".join(_attr_chain(base))
                    elif isinstance(base, ast.Name) \
                            and base.id in aliased:
                        hit = (f"{base.id} (aliased from an attribute"
                               f" at line {aliased[base.id]})")
                    if hit is None:
                        continue
                    line = lines[node.lineno - 1] \
                        if node.lineno <= len(lines) else ""
                    if _allowed(allow, "no-inplace-on-host-views",
                                relfile, line):
                        continue
                    out.append(Finding(
                        "no-inplace-on-host-views",
                        f"{relfile}:{node.lineno}",
                        f"region write into {hit} — overlay/result "
                        "metrics cross to host as read-only views of "
                        "device arrays; REPLACE the array "
                        "(.replace(field=new)) instead of writing "
                        "into it (the PR-5 poison bug class)",
                        path=line.strip()))
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    name = node.targets[0].id
                    if aliasing_binding(node.value, aliased):
                        aliased[name] = node.lineno
                    else:
                        aliased.pop(name, None)
            # recurse into compound statements with the same scope
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(node, attr, None)
                if sub and not isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                    visit(sub, aliased)
            for h in getattr(node, "handlers", []) or []:
                visit(h.body, aliased)
            for case in getattr(node, "cases", []) or []:
                visit(case.body, aliased)   # match statements

    visit(tree.body, {})
    return out


# ---- rule: journal-before-mutation -----------------------------------
def _walk_local(fn):
    """Walk a function's OWN statements, not those of nested defs —
    a setter inside a nested function must be judged against that
    function's journal appends, not the enclosing one's."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _check_journal_order(tree, lines, relfile, allow) -> list[Finding]:
    out = []
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # a def nested inside another def is a DEFERRED body — textual
    # domination is meaningless there (the journal append lives at
    # the call site), so the rule only judges top-level fns/methods
    nested = {inner for fn in fns for inner in ast.walk(fn)
              if inner is not fn
              and isinstance(inner, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
    for fn in fns:
        if fn in nested:
            continue
        appends = []   # linenos of journal.outcome(...) appends
        setters = []   # (node, chain) of terminal-status calls
        for node in _walk_local(fn):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            if chain[-1] == "outcome" and "journal" in chain[:-1]:
                appends.append(node.lineno)
            elif chain[-1] in _TERMINAL_SETTERS:
                setters.append((node, chain))
        for node, chain in setters:
            # textual domination within the function: the append must
            # come first on the path (same-line counts — the append
            # guard wraps the setter's own statement in practice)
            if any(jl <= node.lineno for jl in appends):
                continue
            line = lines[node.lineno - 1] \
                if node.lineno <= len(lines) else ""
            if _allowed(allow, "journal-before-mutation", relfile,
                        line):
                continue
            out.append(Finding(
                "journal-before-mutation",
                f"{relfile}:{node.lineno}",
                f".{chain[-1]}() makes a terminal status visible in "
                f"{fn.name}() with no preceding journal.outcome() "
                "append — a crash between the two re-runs (or loses) "
                "the request on recovery (the PR-12 crash window, "
                "docs/SERVING.md)",
                path=fn.name))
    return out


# ---- driver ----------------------------------------------------------
def lint(rules=None) -> list[Finding]:
    allow, findings = load_allowlist()

    def want(r):
        return rules is None or r in rules

    if want("no-wall-clock-in-pure-paths"):
        for rel in PURE_PATH_MODULES:
            tree, lines = _read_lines(os.path.join(REPO_ROOT, rel))
            findings += _check_pure_paths(tree, lines, rel, allow)
        for rel, funcs in RING_ORDER_FUNCS.items():
            tree, lines = _read_lines(os.path.join(REPO_ROOT, rel))
            findings += _check_pure_paths(tree, lines, rel, allow,
                                          funcs=funcs)
    if want("host-staging-is-numpy"):
        for rel, funcs in HOST_STAGING_FUNCS.items():
            tree, lines = _read_lines(os.path.join(REPO_ROOT, rel))
            findings += _check_host_staging(tree, lines, rel, funcs,
                                            allow)
    if want("no-inplace-on-host-views"):
        for rel in HOST_VIEW_MODULES:
            tree, lines = _read_lines(os.path.join(REPO_ROOT, rel))
            findings += _check_host_views(tree, lines, rel, allow)
    if want("journal-before-mutation"):
        for rel in JOURNAL_ORDER_MODULES:
            tree, lines = _read_lines(os.path.join(REPO_ROOT, rel))
            findings += _check_journal_order(tree, lines, rel, allow)
    return findings


def raw_findings(rule: str, relfile: str) -> list[Finding]:
    """One rule over one repo file, allowlist IGNORED — the audit
    trail's other half: tests use this to prove every allowlist entry
    still masks a live finding (a stale entry hides nothing and must
    be dropped), whatever rule the entry belongs to."""
    tree, lines = _read_lines(os.path.join(REPO_ROOT, relfile))
    if rule == "no-wall-clock-in-pure-paths":
        return _check_pure_paths(tree, lines, relfile, [],
                                 funcs=RING_ORDER_FUNCS.get(relfile))
    if rule == "host-staging-is-numpy":
        return _check_host_staging(
            tree, lines, relfile, HOST_STAGING_FUNCS.get(relfile, ()),
            [])
    if rule == "no-inplace-on-host-views":
        return _check_host_views(tree, lines, relfile, [])
    if rule == "journal-before-mutation":
        return _check_journal_order(tree, lines, relfile, [])
    raise ValueError(f"unknown AST rule {rule!r}")


# ---- fixture entry points (used by tests/test_analysis.py) -----------
def lint_source(src: str, relfile: str = "<fixture>.py",
                rule: str = "no-wall-clock-in-pure-paths",
                staging_funcs=(), pure_funcs=None) -> list[Finding]:
    """Run ONE rule over an in-memory source string — the violation
    fixtures prove each rule actually fires without planting broken
    code in the tree.  ``pure_funcs`` scopes the no-wall-clock rule
    to named function bodies (the RING_ORDER_FUNCS form); None keeps
    the whole-module form."""
    tree = ast.parse(src)
    lines = src.splitlines()
    if rule == "no-wall-clock-in-pure-paths":
        return _check_pure_paths(tree, lines, relfile, [],
                                 funcs=pure_funcs)
    if rule == "host-staging-is-numpy":
        return _check_host_staging(tree, lines, relfile,
                                   tuple(staging_funcs), [])
    if rule == "no-inplace-on-host-views":
        return _check_host_views(tree, lines, relfile, [])
    if rule == "journal-before-mutation":
        return _check_journal_order(tree, lines, relfile, [])
    raise ValueError(f"unknown AST rule {rule!r}")
