"""Runtime guards: transfer and recompile invariants, enforced live.

The static passes prove structure; these context managers prove the
two runtime invariants the stack's serving numbers depend on:

* **No implicit transfers in device-resident segments.**
  :func:`no_implicit_transfers` wraps ``jax.transfer_guard`` — under
  it, any *implicit* host->device conversion (a numpy array sliding
  into a jitted call, an eager op on host data) raises, while the
  explicit, intended transfers (``jax.device_put``/``device_get``,
  the staged jit-call inputs placed before the guard) pass.  The
  fleet resolve path is required to be device-op-free (PERF §11) —
  a tier-1 test runs a small fleet's wait/resolve under this guard.

* **Zero fresh compiles in a steady-state lap.**
  :class:`CompileCounter` counts XLA compiles by filtering jax's
  ``jax_log_compiles`` log records (and swallows them, so enabling
  the counter does not spray WARNINGs); :func:`compile_budget`
  raises :class:`RecompileBudget` when a block compiles more than
  its budget.  ``bench.py --check`` runs a warmed bench lap under a
  zero budget (:func:`steady_state_compile_gate`): a recompile in
  steady state means a cache key regressed or a shape leaked —
  the first-lap discipline of PERF §11 as a gate instead of a
  measurement footnote.
"""

from __future__ import annotations

import logging
from contextlib import contextmanager

from . import Finding

#: the jax loggers that emit compile records under jax_log_compiles
_JAX_COMPILE_LOGGERS = ("jax._src.interpreters.pxla",
                        "jax._src.dispatch")


class RecompileBudget(RuntimeError):
    """A guarded block compiled more programs than its budget."""


class CompileCounter(logging.Filter):
    """Counts ``Compiling <name> ...`` records while attached.

    Implemented as a logging *filter* on the emitting jax loggers:
    filters see every record first and — by rejecting them — also
    keep the temporarily-enabled ``jax_log_compiles`` WARNINGs out
    of the user's terminal.  ``swallow=False`` lets them through
    (debug mode).
    """

    def __init__(self, swallow: bool = True):
        super().__init__()
        self.swallow = swallow
        self.names: list[str] = []

    @property
    def count(self) -> int:
        return len(self.names)

    def filter(self, record: logging.LogRecord) -> bool:
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self.names.append(msg.split(" ", 2)[1])
        return not self.swallow


@contextmanager
def count_compiles(swallow: bool = True):
    """Yield a :class:`CompileCounter` active for the block."""
    import jax
    counter = CompileCounter(swallow=swallow)
    prev = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    loggers = [logging.getLogger(n) for n in _JAX_COMPILE_LOGGERS]
    for lg in loggers:
        lg.addFilter(counter)
    try:
        yield counter
    finally:
        for lg in loggers:
            lg.removeFilter(counter)
        jax.config.update("jax_log_compiles", prev)


@contextmanager
def compile_budget(max_compiles: int = 0, what: str = "guarded block"):
    """Raise :class:`RecompileBudget` when the block exceeds its
    compile budget (0 = a fully warm path must stay warm)."""
    with count_compiles() as counter:
        yield counter
    if counter.count > max_compiles:
        raise RecompileBudget(
            f"{what}: {counter.count} XLA compile(s) against a budget "
            f"of {max_compiles} — compiled: {counter.names} (a steady-"
            "state recompile means a cache key regressed or an input "
            "shape leaked; see docs/ANALYSIS.md "
            "no-recompile-steady-state)")


@contextmanager
def no_implicit_transfers():
    """``jax.transfer_guard("disallow")``: implicit transfers raise,
    explicit device_put/device_get pass.  Wrap device-resident
    segments (an in-flight program's wait + resolve) with this."""
    import jax
    with jax.transfer_guard("disallow"):
        yield


def steady_state_compile_gate(inject_recompile: bool = False) -> dict:
    """The bench.py --check recompile gate.

    Builds the small overlay bench shape, warms it (one full
    run_bench lap — compiles + eager-op programs), then runs TWO more
    laps under a ZERO compile budget.  Returns
    ``{"ok", "compiles", "compiled"}``; ``inject_recompile=True``
    deliberately runs a fresh shape inside the guarded lap to prove
    the gate trips (the acceptance fixture — bench.py exposes it as
    ``--inject-recompile``).
    """
    from ..config import SimConfig
    from ..models.overlay import OverlaySimulation
    cfg = SimConfig(model="overlay", max_nnb=256, total_ticks=48,
                    churn_rate=0.2, rejoin_after=None, seed=11,
                    step_rate=8.0 / 256)
    OverlaySimulation(cfg).run()                # warm lap (untimed)
    # a second seed rides the SAME compiled program (the run cache
    # keys config shape, seeds flow through the schedule) and warms
    # any remaining eager-op programs
    OverlaySimulation(cfg.replace(seed=12)).run()
    try:
        with compile_budget(0, what="steady-state bench lap") as c:
            OverlaySimulation(cfg.replace(seed=13)).run()
            OverlaySimulation(cfg.replace(seed=14)).run()
            if inject_recompile:
                # a FRESH shape mid-lap: guaranteed compile, proving
                # the gate fires (never reached on the clean path)
                OverlaySimulation(cfg.replace(max_nnb=128,
                                              step_rate=8.0 / 128,
                                              seed=15)).run()
    except RecompileBudget as e:
        return {"ok": False, "compiles": c.count, "compiled": c.names,
                "detail": str(e)}
    return {"ok": True, "compiles": c.count, "compiled": c.names}


def self_check(rules=None) -> list[Finding]:
    """CLI-facing guard-pass self check: the counter counts, the
    budget trips, the transfer guard bites.  Proves the guard
    machinery works in THIS process (the real enforcement points are
    bench.py --check and the tier-1 tests)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    findings = []

    def want(r):
        return rules is None or r in rules

    if want("no-recompile-steady-state"):
        f = jax.jit(lambda x: x * 3 + 1)
        f(jnp.ones(7))                          # warm
        with count_compiles() as c:
            f(jnp.ones(7))                      # warm call: 0 compiles
        if c.count != 0:
            findings.append(Finding(
                "no-recompile-steady-state", "guards.self_check",
                f"warm jit call counted {c.count} compiles — the "
                "compile counter is broken on this jax version"))
        tripped = False
        try:
            with compile_budget(0, what="self-check"):
                f(jnp.ones(9))                  # fresh shape: compile
        except RecompileBudget:
            tripped = True
        if not tripped:
            findings.append(Finding(
                "no-recompile-steady-state", "guards.self_check",
                "an injected recompile did NOT trip the zero budget "
                "— the bench.py --check gate would be blind"))

    if want("no-implicit-transfer-in-resolve"):
        g = jax.jit(lambda x: x + 1)
        g(jnp.ones(3))                          # warm
        bit = False
        try:
            with no_implicit_transfers():
                g(np.ones(3))                   # implicit h2d
        except Exception:
            bit = True
        if not bit:
            findings.append(Finding(
                "no-implicit-transfer-in-resolve", "guards.self_check",
                "an implicit numpy->jit transfer passed under "
                "transfer_guard('disallow') — the guard is inert on "
                "this backend"))
        try:
            with no_implicit_transfers():
                jax.device_get(g(jax.device_put(np.ones(3))))
        except Exception as e:
            findings.append(Finding(
                "no-implicit-transfer-in-resolve", "guards.self_check",
                f"explicit device_put/device_get raised under the "
                f"guard ({e}) — the guard would flag the intended "
                "staged transfers"))
    return findings
